"""Shard lint: static partition-plan analysis + compiled-placement census.

The third tier-1 static gate, beside the graph lint (round 3) and the
thread lint (round 9).  Since the rules engine (``parallel/rules.py``)
became the source of every sharding, exchange, codec and serving-KV
plan, a dead or shadowed rule, a silently-replicated large tensor, or a
GSPMD-inserted resharding collective only surfaced as a perf regression
on hardware we don't have.  This module makes those defects findings,
in two halves sharing the round-3 findings/suppression/baseline model:

**Plan lint** (:func:`lint_plan`) — pure-host analysis of an ordered
rule list against a target pytree, no mesh and no jax trace required:

* ``invalid-regex`` (error) — a pattern that does not compile;
* ``duplicate-pattern`` (error) — an identical pattern repeated after
  an earlier occurrence with a *concrete* value (first-match-wins makes
  it unreachable; repeats after a *callable* occurrence are the legal
  decline-chain idiom ``zero_state_rules`` uses) — the same spelling
  ``rules.compile_rules`` now rejects at plan build;
* ``dead-rule`` (error) — a pattern matching no leaf path in the tree
  (the typo'd rule that silently replicates its target);
* ``shadowed-rule`` (warn) — a rule whose every pattern match is first
  claimed by earlier rules, so it can never fire;
* ``axis-divisibility`` (error) — a leaf dimension not divisible by the
  product of the mesh-axis sizes its winning PartitionSpec entry names
  (the round-14 ``serving_kv_axis`` construction check generalized to
  every rule and run WITHOUT a mesh, from declared ``axis_sizes``);
* ``replicated-giant`` (warn) — a leaf no rule claims, above a byte
  threshold: under ShardingPlan semantics it silently replicates on
  every device.

**Placement census** (:func:`placement_census`) — walk one traced lint
target (``analysis/targets.py``, the same plumbing as ``ir_lint``) and
record every input tensor's *compiled* sharding (explicit arguments via
the executable's input shardings; closed-over parameters — the serving
engines capture their weights — via the jaxpr consts' live shardings)
plus a per-device byte ledger derived from the shard shapes.  The table
is pinned exactly in ``scripts/shard_budget.json`` (``shard-budget``,
error, mirroring ``comm-budget``), so "the plan and the compiled
program agree" is a diffable CI artifact.  Alongside, the
``resharding-collective`` rule (warn — ratchetable through
``scripts/lint_baseline.json``, the explicitly-justified ledger) flags
compiled all-gathers / collective-permutes / all-to-alls *not
attributable to a declared exchange*: attributable means the op's HLO
metadata name stack carries a declared scope (the zero stages'
scatter/gather scopes, ``exchange/``, an explicit
``sharding_constraint``) or ends in an explicit collective primitive
the author spelled out (``all_gather``/``psum``/``all_to_all``/...,
underscore-spelled — GSPMD-*inserted* reshardings instead carry the
consumer op they were materialized for: ``dot_general``, ``mul``,
``pad``, ...).  A dropped ``with_sharding_constraint`` turns a declared
gather into exactly such an unattributed one, which is how the gate
catches it.

Wired into ``scripts/graph_lint.py`` (default run and ``--shardings``)
and tier-1 (``tests/test_shard_lint.py``,
``tests/test_budget_guards.py``); rule catalogue in docs/graph_lint.md.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Sequence

from distkeras_tpu.analysis.findings import Finding

DEFAULT_GIANT_BYTES = 1 << 20

# ------------------------------------------------------------ plan lint

_DTYPE_SHORT = {
    "float32": "f32", "float64": "f64", "float16": "f16",
    "bfloat16": "bf16", "int8": "s8", "int16": "s16", "int32": "s32",
    "int64": "s64", "uint8": "u8", "uint16": "u16", "uint32": "u32",
    "uint64": "u64", "bool": "pred",
}


def _concrete(val) -> bool:
    # ONE concreteness predicate with the engine's build-time
    # duplicate rejection (rules._is_concrete) — the two must never
    # diverge or compile_rules and the duplicate-pattern lint would
    # disagree about which repeats are the legal decline-chain idiom.
    from distkeras_tpu.parallel.rules import _is_concrete

    return _is_concrete(val)


def _spec_of(val):
    """The PartitionSpec a rule value places, if it places one (plain
    specs and NamedShardings); None for codec strings etc."""
    from jax.sharding import PartitionSpec as P

    if isinstance(val, P):
        return val
    spec = getattr(val, "spec", None)
    if isinstance(spec, P):
        return spec
    return None


def _spec_str(spec) -> str:
    """THE spelling of a PartitionSpec in findings AND census rows —
    one definition so plan-lint messages and shard_budget.json can
    never drift apart."""
    return "P(" + ", ".join(repr(e) for e in tuple(spec)) + ")"


def _value_str(val) -> str:
    spec = _spec_of(val)
    if spec is not None:
        return _spec_str(spec)
    if not _concrete(val):
        return "<callable>"
    return repr(val)


def _leaf_bytes_of(shape, dtype) -> int:
    import numpy as np

    try:
        itemsize = dtype.itemsize
    except Exception:  # noqa: BLE001 — exotic dtype: assume 4
        itemsize = 4
    return int(np.prod(shape)) * itemsize if shape else itemsize


def _leaf_bytes(leaf) -> int:
    shape = getattr(leaf, "shape", None)
    if shape is None:
        return 0
    return _leaf_bytes_of(shape, leaf.dtype)


def _iter_rules(rules) -> list:
    """Normalize a rule source — a ShardingPlan (compiled rules), a
    plain ``[(pattern, value)]`` list, or a pre-compiled list — into
    ``[(pattern_str, value)]``."""
    items = getattr(rules, "rules", rules)
    out = []
    for pat, val in items:
        out.append((pat if isinstance(pat, str) else pat.pattern, val))
    return out


def lint_plan(rules, tree, *, name: str,
              axis_sizes: dict | None = None,
              giant_bytes: int = DEFAULT_GIANT_BYTES,
              ) -> list[Finding]:
    """Statically analyze one rule list against the pytree it places.

    ``rules`` — a ShardingPlan, or ordered ``(pattern, value)`` pairs
    (values may be PartitionSpecs, NamedShardings, codec names, or
    callable rules, exactly the engine's rule language).  ``tree`` —
    the target pytree (live arrays or ``ShapeDtypeStruct``s; only
    ``.shape``/``.dtype`` are read, nothing executes).  ``name`` labels
    the findings (the ``path`` field, like IR findings use the trace
    target name).  ``axis_sizes`` — declared mesh-axis sizes (e.g.
    ``{"data": 4, "model": 2}``) for the divisibility check; axes not
    listed (and ``None``) skip it, so the lint runs mesh-free.

    Callable rules are *evaluated* per leaf (they are pure shape/path
    policies); one that raises is conservatively treated as claiming
    the leaf, so no downstream rule is mis-reported.
    """
    import jax

    from distkeras_tpu.parallel.rules import _axes_of, leaf_name

    findings: list[Finding] = []

    def add(rule, sev, msg, hint=""):
        findings.append(Finding(rule=rule, severity=sev, path=name,
                                line=None, message=msg, hint=hint))

    norm: list[tuple] = []          # (pattern_str, compiled|None, value)
    claimed: dict[str, bool] = {}
    duplicates: set[int] = set()
    for i, (pat_s, val) in enumerate(_iter_rules(rules)):
        if claimed.get(pat_s):
            duplicates.add(i)
            add("duplicate-pattern", "error",
                f"rule {i} ({pat_s!r}, {_value_str(val)}) repeats a "
                "pattern an earlier rule with a concrete value already "
                "spells — first-match-wins makes it unreachable",
                "remove one of the duplicates (compile_rules rejects "
                "this shape at plan build)")
        claimed[pat_s] = claimed.get(pat_s, False) or _concrete(val)
        try:
            comp = re.compile(pat_s)
        except re.error as e:
            add("invalid-regex", "error",
                f"rule {i} pattern {pat_s!r} does not compile: {e}",
                "fix the regex — compile_rules raises the same error "
                "at plan construction")
            comp = None
        norm.append((pat_s, comp, val))

    leaves = [(leaf_name(p), leaf) for p, leaf
              in jax.tree_util.tree_flatten_with_path(tree)[0]]
    matched: list[list] = [[] for _ in norm]     # pattern-level matches
    consulted: list[set] = [set() for _ in norm]  # reached first-match
    winners: list[tuple] = []   # (leaf name, leaf, rule idx, spec|None)
    unmatched: list[tuple] = []
    for lname, leaf in leaves:
        won = False
        for i, (pat_s, comp, val) in enumerate(norm):
            if comp is None or comp.search(lname) is None:
                continue
            matched[i].append(lname)
            if won:
                continue
            consulted[i].add(lname)
            if _concrete(val):
                winners.append((lname, leaf, i, _spec_of(val)))
                won = True
            else:
                try:
                    out = val(lname, leaf)
                except Exception:  # noqa: BLE001 — see docstring
                    winners.append((lname, leaf, i, None))
                    won = True
                else:
                    if out is not None:
                        winners.append((lname, leaf, i, _spec_of(out)))
                        won = True
        if not won:
            unmatched.append((lname, leaf))

    for i, (pat_s, comp, val) in enumerate(norm):
        if comp is None or i in duplicates:
            # A duplicate is already reported once, at the defect:
            # shadowed/dead findings for the same rule would double-
            # count one authoring bug in the ratchet ledger.
            continue
        if not matched[i]:
            add("dead-rule", "error",
                f"rule {i} ({pat_s!r}, {_value_str(val)}) matches no "
                "leaf in the target tree",
                "a typo'd pattern places nothing and its target leaf "
                "silently falls through — fix the pattern or drop the "
                "rule")
        elif not consulted[i]:
            mset = set(matched[i])
            covering = sorted({w_i for lname, _, w_i, _ in winners
                               if lname in mset})
            cov = ", ".join(f"rule {j} ({norm[j][0]!r})"
                            for j in covering[:3])
            ex = ", ".join(repr(l) for l in matched[i][:3])
            add("shadowed-rule", "warn",
                f"rule {i} ({pat_s!r}, {_value_str(val)}) is fully "
                f"shadowed: every leaf it matches ({ex}) is first "
                f"claimed by {cov}",
                "reorder the rules (first-match-wins) or delete the "
                "shadowed one")

    if axis_sizes:
        for lname, leaf, i, spec in winners:
            shape = getattr(leaf, "shape", None)
            if spec is None or shape is None:
                continue
            spec_t = tuple(spec)
            if len(spec_t) > len(shape):
                add("axis-divisibility", "error",
                    f"rule {i} ({norm[i][0]!r}, {_value_str(spec)}) "
                    f"names {len(spec_t)} dimensions but leaf "
                    f"{lname!r} has rank {len(shape)}",
                    "the spec would fail at device_put; match the "
                    "leaf's rank")
                continue
            for d, entry in enumerate(spec_t):
                size = 1
                axes = [a for a in _axes_of(entry) if a in axis_sizes]
                for a in axes:
                    size *= int(axis_sizes[a])
                if size > 1 and shape[d] % size:
                    add("axis-divisibility", "error",
                        f"rule {i} ({norm[i][0]!r}, "
                        f"{_value_str(spec)}) shards dim {d} of leaf "
                        f"{lname!r} (shape {tuple(shape)}) over "
                        f"{'x'.join(axes)} (size {size}), which does "
                        f"not divide {shape[d]}",
                        "shrink the axis, pick a divisible dimension, "
                        "or leave the leaf replicated")

    # Plans with an fsdp_axis scatter unmatched leaves too
    # (ShardingPlan.spec_for runs _augment_fsdp on every spec,
    # including the P() an unmatched leaf falls back to), so
    # "unmatched" only means "replicated" when the augmentation would
    # decline the leaf — reuse the REAL augmentation to decide.
    fsdp_axis = getattr(rules, "fsdp_axis", None)

    def fsdp_shards(leaf) -> bool:
        from jax.sharding import PartitionSpec as P

        from distkeras_tpu.parallel.sharding import _augment_fsdp

        if fsdp_axis is None:
            return False
        shape = getattr(leaf, "shape", None)
        size = (axis_sizes or {}).get(fsdp_axis)
        if size is None:
            # Axis size undeclared: cannot prove replication — no warn.
            return True
        return _augment_fsdp(P(), shape, int(size), fsdp_axis) != P()

    for lname, leaf in unmatched:
        nbytes = _leaf_bytes(leaf)
        if nbytes > giant_bytes and not fsdp_shards(leaf):
            add("replicated-giant", "warn",
                f"no rule claims leaf {lname!r} ({nbytes} bytes) — "
                "under plan semantics it replicates in full on every "
                "device",
                "add a rule (or an explicit ('.*', P()) catch-all if "
                "replication is intended — intent should be spelled, "
                "not defaulted)")
    return findings


# ------------------------------------------- the shipped-plan matrix


def plan_suite() -> list[tuple]:
    """``(name, rules, tree, axis_sizes)`` for every shipped plan
    constructor against the real trees it places — the dry-run matrix
    ``tests/test_shard_lint.py`` pins (no shipped plan may carry a dead
    or shadowed rule).  Trees are ``eval_shape`` structs: nothing
    touches a device.  The transformer tree is the union of a dense and
    an MoE config so the MoE rules are live; axis sizes are the
    standard analysis meshes (8-way data for training, 4x2 for the
    pod-sharded serving plan)."""
    import jax
    import jax.numpy as jnp
    import optax

    from distkeras_tpu.analysis.targets import _lm_cfg
    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.parallel.collectives import zero1_shard_shapes
    from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh
    from distkeras_tpu.parallel.rules import zero_state_rules
    from distkeras_tpu.parallel.sharding import serving_plan

    cfg = _lm_cfg()
    dense = jax.eval_shape(
        lambda: tfm.init_params(jax.random.key(0), cfg))
    moe_cfg = dataclasses.replace(cfg, num_experts=2)
    moe = jax.eval_shape(
        lambda: tfm.init_params(jax.random.key(0), moe_cfg))
    lm_union = {"dense": dense, "moe": moe}
    serving_axes = {"data": 4, "model": 2}

    mesh = make_mesh(MeshSpec())   # the 8-way data mesh tier-1 uses

    def adam_state_over_views(params):
        """The real ZeRO optimizer-state tree: adam over the [n, cols]
        shard views the sharded update actually sees."""
        shapes = sorted(zero1_shard_shapes(jax.tree.leaves(params), 8))
        views = [jax.ShapeDtypeStruct(s, jnp.float32) for s in shapes]
        return jax.eval_shape(optax.adam(1e-3).init, views)

    # The ADAG flagship MLP's leaf shapes (analysis/targets.py).
    mlp = [jax.ShapeDtypeStruct(s, jnp.float32)
           for s in ((8, 16), (16,), (16, 8), (8,))]
    mlp_state = adam_state_over_views(mlp)
    lm_state = adam_state_over_views(dense)

    return [
        ("serving_plan", serving_plan(), lm_union, serving_axes),
        ("tp_rules", tfm.tp_rules(), lm_union, serving_axes),
        ("fsdp_plan+tp_rules", serving_plan(fsdp_axis="data"),
         lm_union, serving_axes),
        ("zero1_plan/state_rules", zero_state_rules(mlp, mesh),
         mlp_state, {"data": 8}),
        ("zero3_plan/state_rules", zero_state_rules(dense, mesh),
         lm_state, {"data": 8}),
        # The shipped per-bucket codec-rule spelling the
        # lmtrainer_rulesef lint target trains with (docs/lowcomm.md).
        ("exchange_codec_rules",
         [("emb", "topk"), (".*", "int8")], dense, None),
    ]


def lint_repo_plans() -> list[Finding]:
    """The plan lint over every shipped plan constructor — what the
    ``graph_lint.py --shardings`` run and the tier-1 matrix execute."""
    out: list[Finding] = []
    for name, rules, tree, axes in plan_suite():
        out += lint_plan(rules, tree, name=name, axis_sizes=axes)
    return out


# ----------------------------------------------- resharding attribution

_RESHARD_OPS = ("all-gather", "collective-permute", "all-to-all")

# An op_name containing one of these is a DECLARED exchange: the zero
# stages' named scatter/gather scopes, the exchange layer's merge
# scopes, or an explicit with_sharding_constraint (the serve path's
# KV pin, the zero constraints).
DECLARED_SCOPES = ("zero1/", "zero2/", "zero3/", "exchange/",
                   "sharding_constraint")

# ... or whose final name-stack component is an explicit collective
# primitive (underscore-spelled in jax name stacks; the author wrote
# the collective).  GSPMD-inserted reshardings instead carry the
# consumer op they materialize an operand for (dot_general, mul, pad,
# broadcast_in_dim, concatenate, ...).
_EXPLICIT_TAILS = frozenset({
    "all_gather", "all_gather_invariant", "all_to_all", "psum",
    "psum_scatter", "pmean", "pmax", "pmin", "ppermute",
    "reduce_scatter", "all_reduce",
})

_RESHARD_RE = re.compile(
    r"[\s)](" + "|".join(_RESHARD_OPS) + r")(?:-start)?\(")
_OPNAME_RE = re.compile(r'op_name="([^"]*)"')


def attributed(op_name: str) -> bool:
    """Is this compiled collective's name stack attributable to a
    declared exchange (see module docstring)?"""
    if any(scope in op_name for scope in DECLARED_SCOPES):
        return True
    return op_name.rsplit("/", 1)[-1] in _EXPLICIT_TAILS


def resharding_census(hlo: str) -> list[dict]:
    """Every all-gather / collective-permute / all-to-all in one
    compiled module: ``{"op", "op_name", "attributed"}`` per instance,
    sorted (op, op_name) so downstream finding order — and therefore
    the warn-baseline ratchet's encounter order — is stable."""
    out = []
    for line in hlo.splitlines():
        m = _RESHARD_RE.search(line)
        if m is None:
            continue
        nm = _OPNAME_RE.search(line)
        op_name = nm.group(1) if nm else ""
        out.append({"op": m.group(1), "op_name": op_name,
                    "attributed": attributed(op_name)})
    return sorted(out, key=lambda r: (r["op"], r["op_name"]))


def reshard_findings(spec, hlo: str) -> list[Finding]:
    """``resharding-collective`` findings for one target: one warn per
    unattributed resharding instance (per-instance so the
    lint_baseline ratchet pins exact counts; known backend artifacts —
    the CPU partitioner's hierarchical AR+permute spelling, the
    fsdp/zero3 gather-on-use materializations — live in that ledger
    with their justification in docs/graph_lint.md)."""
    out = []
    for rec in resharding_census(hlo):
        if rec["attributed"]:
            continue
        tail = rec["op_name"].rsplit("/", 1)[-1] or "<no metadata>"
        out.append(Finding(
            rule="resharding-collective", severity="warn",
            path=spec.name, line=None,
            message=(f"GSPMD-inserted {rec['op']} not attributable to "
                     f"a declared sharding scope (op_name tail "
                     f"`{tail}`)"),
            hint="a resharding the plan did not declare moves bytes "
                 "every step; add/restore the with_sharding_constraint "
                 "or named scope that owns it, or — for a known "
                 "backend artifact — record it in the "
                 "lint_baseline.json ratchet with a docs/graph_lint.md "
                 "justification",
            suppressed="resharding-collective" in spec.suppress))
    return out


# ------------------------------------------------- placement census


def _shape_str(shape, dtype) -> str:
    short = _DTYPE_SHORT.get(str(dtype), str(dtype))
    return f"{short}[{','.join(str(d) for d in shape)}]"


def _placement_str(sh) -> str:
    spec = getattr(sh, "spec", None)
    if spec is not None:
        return _spec_str(spec)
    if getattr(sh, "is_fully_replicated", False):
        return "P()"
    return type(sh).__name__


def _per_device_bytes(sh, shape, dtype) -> int:
    try:
        local = sh.shard_shape(tuple(shape))
    except Exception:  # noqa: BLE001 — shardless leaf: counts in full
        local = tuple(shape)
    return _leaf_bytes_of(local, dtype)


def placement_census(spec, artifacts) -> dict:
    """The compiled placement table of one lint target.

    Explicit arguments come from the executable's input shardings
    (named ``args/<flattened key path>``); closed-over tensors — the
    serving engines capture their parameters — from the jaxpr consts'
    live shardings (named ``const/<i>`` in trace order, shape/dtype
    recorded so the table diffs readably).  Per-device bytes are
    computed from each sharding's shard shape — replicated leaves count
    in full, sharded leaves 1/n — the same accounting
    ``engine.memory_footprint()`` reads off live addressable shards
    (cross-checked in tests/test_budget_guards.py).
    """
    import jax

    from distkeras_tpu.parallel.rules import leaf_name

    closed, compiled = artifacts.closed, artifacts.compiled
    # None appears on BOTH sides — as an empty argument (a disabled
    # rng, an absent segment tree) and, on the sharding side only, as
    # the marker for an argument jit pruned (unused in the program).
    # Flattening both trees with None-as-leaf keeps them aligned.
    arg_leaves = jax.tree_util.tree_flatten_with_path(
        spec.args, is_leaf=lambda x: x is None)[0]
    shardings = jax.tree_util.tree_leaves(
        compiled.input_shardings[0],
        is_leaf=lambda x: x is None or isinstance(x,
                                                  jax.sharding.Sharding))
    if len(shardings) != len(arg_leaves):
        raise RuntimeError(
            f"{spec.name}: {len(shardings)} compiled input shardings "
            f"for {len(arg_leaves)} argument leaves — the census "
            "cannot align them")
    tensors: dict[str, list] = {}

    def record(name, shape, dtype, sh):
        if sh is None:
            # Pruned input: the program never reads it, so XLA assigns
            # no placement; it still persists between steps, so the
            # ledger counts its full bytes.
            tensors[name] = [_shape_str(shape, dtype), "pruned",
                             _leaf_bytes_of(shape, dtype)]
            return
        tensors[name] = [_shape_str(shape, dtype), _placement_str(sh),
                         _per_device_bytes(sh, shape, dtype)]

    for (path, leaf), sh in zip(arg_leaves, shardings):
        if leaf is None:
            continue   # empty argument slot, not a tensor
        record("args/" + leaf_name(path), leaf.shape, leaf.dtype, sh)
    for i, const in enumerate(closed.consts):
        shape = getattr(const, "shape", None)
        if shape is None or len(shape) == 0:
            continue   # scalar bookkeeping constants, not tensors
        sh = getattr(const, "sharding", None)
        if sh is None:
            # A host-side constant (plain numpy closure): live and
            # effectively replicated — distinct from a pruned ARG,
            # which the program never reads.
            tensors[f"const/{i}"] = [_shape_str(shape, const.dtype),
                                     "host-const",
                                     _leaf_bytes_of(shape, const.dtype)]
            continue
        record(f"const/{i}", shape, const.dtype, sh)

    census = resharding_census(artifacts.hlo) if artifacts.hlo else []
    return {
        "tensors": tensors,
        "bytes_global": sum(_leaf_bytes(l) for _, l in arg_leaves)
        + sum(_leaf_bytes(c) for c in closed.consts
              if getattr(c, "ndim", 0)),
        "bytes_per_device": sum(v[2] for v in tensors.values()),
        "resharding": {
            "attributed": sum(r["attributed"] for r in census),
            "unattributed": sum(not r["attributed"] for r in census),
        },
    }


# ----------------------------------------------------------- budgets


def check_shard_budget(name: str, entry: dict, budgets: dict
                       ) -> list[Finding]:
    """Compare one target's placement census against the checked-in
    ``scripts/shard_budget.json``.  Any drift — a tensor's placement,
    shape, per-device bytes, the byte totals, or the resharding
    attribution counts — is an error finding; re-record deliberate
    changes with ``graph_lint.py --update-budgets`` and review the
    JSON diff (the diff IS the placement review)."""
    want = budgets.get(name)
    if want is None:
        return [Finding(
            rule="shard-budget", severity="error", path=name, line=None,
            message="no placement budget recorded for this target",
            hint="run scripts/graph_lint.py --update-budgets")]
    if want == entry:
        return []
    got_t, want_t = entry.get("tensors", {}), want.get("tensors", {})
    changed = sorted(
        set(k for k in got_t if got_t[k] != want_t.get(k))
        | (set(want_t) - set(got_t)))
    detail = ", ".join(changed[:4]) + ("..." if len(changed) > 4 else "")
    return [Finding(
        rule="shard-budget", severity="error", path=name, line=None,
        message=(f"compiled placements drifted from the budget: "
                 f"{len(changed)} tensor(s) changed ({detail}); "
                 f"per-device bytes {want.get('bytes_per_device')} -> "
                 f"{entry.get('bytes_per_device')}, resharding "
                 f"{want.get('resharding')} -> "
                 f"{entry.get('resharding')}"),
        hint="if the placement change is intentional, re-record with "
             "scripts/graph_lint.py --update-budgets and review the "
             "scripts/shard_budget.json diff")]


def load_shard_budgets(path: str) -> dict:
    import json

    with open(path) as f:
        return json.load(f)["targets"]


def save_shard_budgets(path: str, budgets: dict,
                       device_count: int | None = None) -> None:
    import json

    import jax

    doc = {
        "comment": "per-tensor compiled placements + per-device byte "
                   "ledger per lint target on the 8-device CPU mesh "
                   "(NOTE: CPU-compiled placements — the AR+slice "
                   "artifact; see the ROADMAP item-5 hardware ledger "
                   "for which rows a TPU session must re-verify); "
                   "re-record with scripts/graph_lint.py "
                   "--update-budgets and review the diff",
        "device_count": (device_count if device_count is not None
                         else jax.device_count()),
        "targets": budgets,
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")


__all__ = ["DEFAULT_GIANT_BYTES", "lint_plan", "plan_suite",
           "lint_repo_plans", "DECLARED_SCOPES", "attributed",
           "resharding_census", "reshard_findings", "placement_census",
           "check_shard_budget", "load_shard_budgets",
           "save_shard_budgets"]
