"""Thread-safety source lint over the threaded core modules.

The static half of the concurrency gate (the dynamic half is
``distkeras_tpu/utils/locks.py``'s runtime sanitizer).  Every recent
concurrency bug this repo shipped was one of a handful of *source
shapes* — a callback fired under a lock (the PR-8 SLO-subscriber
deadlock), blocking work while holding a lock, a raw un-instrumented
lock the sanitizer can't see — so this lint turns those shapes into
AST rules over the packages that actually run threads
(``serving/``, ``obs/``, ``resilience/``, ``data/prefetch.py``,
``utils/misc.py``, ``utils/locks.py``, ``native/``):

===================  =====  ==============================================
rule id              sev    fires on
===================  =====  ==============================================
raw-lock             error  ``threading.Lock()`` / ``threading.RLock()``
                            / ``threading.Condition()`` constructed in a
                            threaded core module instead of the
                            instrumented :func:`~distkeras_tpu.utils.
                            locks.TracedLock` / ``TracedRLock`` wrappers
                            (allowlist: ``utils/locks.py`` itself — the
                            wrappers have to be built out of something)
lock-callback        error  a registered callback / subscriber / hook
                            invoked lexically inside a ``with <lock>:``
                            block — the callee can re-enter the subsystem
                            and deadlock on the very lock the caller
                            holds (the exact PR-8 shape:
                            ``for fn in self._subscribers: fn(...)``
                            under the engine lock)
lock-blocking        warn   a blocking call while holding a lock:
                            ``time.sleep``, ``subprocess.*``, HTTP/socket
                            reads (``urlopen``/``recv``/``accept``), a
                            thread ``join``, an event ``wait`` — every
                            other thread needing the lock stalls for the
                            full blocking duration
lock-double-acquire  error  a ``with <lock>:`` lexically nested inside a
                            ``with <same lock>:`` in one function, where
                            the module constructs that lock NON-reentrant
                            (``TracedLock``/``threading.Lock``) — a
                            certain same-thread deadlock
===================  =====  ==============================================

The analysis is *lexical* (per function body): a def nested inside a
``with lock:`` block runs later, not under the lock, and is excluded;
calls reached through another function while the lock is held are the
dynamic sanitizer's job.  "Callback-shaped" callees are (a) a bare
name bound by a ``for`` over a collection whose name ends in
``subscribers``/``callbacks``/``hooks``/``listeners`` (through
``list()``/``tuple()``/``sorted()``/``reversed()`` wrappers), or (b)
any callee whose final name matches that family.  Suppress per line
with ``# dkt: ignore[rule]`` (findings.py); ``lock-blocking`` warns
participate in the ``scripts/lint_baseline.json`` ratchet like every
other warn rule.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterable

from distkeras_tpu.analysis.findings import Finding, apply_suppressions
from distkeras_tpu.analysis.source_lint import _attr_chain, iter_py_files

# The threaded scope: packages/modules that create threads or locks.
_THREADED_DIRS = tuple(
    os.path.join("distkeras_tpu", d)
    for d in ("serving", "obs", "resilience", "native"))
_THREADED_FILES = tuple(
    os.path.join("distkeras_tpu", f)
    for f in (os.path.join("data", "prefetch.py"),
              os.path.join("utils", "misc.py"),
              os.path.join("utils", "locks.py")))
# The one legal home of raw lock construction: the wrappers themselves.
_RAW_LOCK_ALLOWLIST = (os.path.join("distkeras_tpu", "utils", "locks.py"),)

_RAW_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_TRACED_RLOCK_CTORS = {"RLock", "TracedRLock"}
_TRACED_LOCK_CTORS = {"Lock", "TracedLock"}

_CALLBACK_RE = re.compile(
    r"(callback|subscriber|listener|hook)s?$", re.IGNORECASE)


def _in_scope(path: str) -> bool:
    norm = path.replace(os.sep, "/")
    return (any(d.replace(os.sep, "/") + "/" in norm
                for d in _THREADED_DIRS)
            or any(norm.endswith(f.replace(os.sep, "/"))
                   for f in _THREADED_FILES))


def _is_lock_expr(node) -> str | None:
    """The dotted chain of a with-item that looks like a lock
    (``self._lock``, ``self._admission_lock``, module-level ``_lock``)
    — the final name must contain "lock"."""
    chain = _attr_chain(node)
    if chain and "lock" in chain[-1].lower():
        return ".".join(chain)
    return None


def _unwrap_iter(node):
    """``list(self._subscribers)`` -> ``self._subscribers`` (also
    tuple/sorted/reversed, one level each)."""
    while (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
           and node.func.id in ("list", "tuple", "sorted", "reversed")
           and len(node.args) == 1):
        node = node.args[0]
    return node


def _callbackish(name: str) -> bool:
    return bool(_CALLBACK_RE.search(name.lstrip("_")))


def _blocking_reason(chain: list[str], name: str) -> str | None:
    """Why this call blocks, or None.  Receiver-sensitive checks
    (``join``/``wait``/``recv``) key off the receiver's name so that
    e.g. ``", ".join(...)`` never fires."""
    if name == "sleep" and (len(chain) == 1 or chain[-2] == "time"):
        return "time.sleep while holding a lock"
    if chain[:1] == ["subprocess"]:
        return f"subprocess.{name} while holding a lock"
    if name == "urlopen":
        return "an HTTP read while holding a lock"
    if name in ("recv", "recvfrom", "accept") and len(chain) >= 2:
        return f"a socket {name} while holding a lock"
    if name == "join" and len(chain) >= 2 \
            and "thread" in chain[-2].lower():
        return "a thread join while holding a lock"
    if name == "wait" and len(chain) >= 2 and any(
            k in chain[-2].lower()
            for k in ("event", "stop", "halt", "done", "cond")):
        return "an event wait while holding a lock"
    return None


def _collect_lock_kinds(tree: ast.Module) -> tuple[set, set]:
    """Names/attrs this module binds to a reentrant vs non-reentrant
    lock constructor (``self._x = TracedRLock()`` -> ``_x`` reentrant).
    Drives ``lock-double-acquire``: only locks this module *provably*
    constructs non-reentrant are flagged."""
    reentrant, nonreentrant = set(), set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        value = node.value
        if not isinstance(value, ast.Call):
            continue
        chain = _attr_chain(value.func)
        ctor = chain[-1] if chain else ""
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for t in targets:
            tchain = _attr_chain(t)
            if not tchain:
                continue
            attr = tchain[-1]
            if ctor in _TRACED_RLOCK_CTORS:
                reentrant.add(attr)
            elif ctor in _TRACED_LOCK_CTORS:
                nonreentrant.add(attr)
    return reentrant, nonreentrant


def _collect_threading_imports(tree: ast.Module) -> tuple[set, set]:
    """Local names the module binds to the ``threading`` module
    (``import threading [as t]``) and to its raw lock constructors
    (``from threading import Lock [as L]``) — so the ``raw-lock``
    rule catches every spelling, not just the literal
    ``threading.Lock()``."""
    mod_aliases, ctor_names = set(), set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "threading":
                    mod_aliases.add(a.asname or "threading")
        elif isinstance(node, ast.ImportFrom) and node.module == "threading":
            for a in node.names:
                if a.name in _RAW_LOCK_CTORS:
                    ctor_names.add(a.asname or a.name)
    return mod_aliases, ctor_names


class _ThreadLinter(ast.NodeVisitor):
    def __init__(self, path: str, source: str):
        self.path = path
        self.lines = source.splitlines()
        self.findings: list[Finding] = []
        self._held: list[str] = []       # with-lock chains, current fn
        self._sub_names: set[str] = set()  # for-targets over callbacks
        self._reentrant: set[str] = set()
        self._nonreentrant: set[str] = set()
        self._thr_aliases: set[str] = set()
        self._thr_ctors: set[str] = set()

    def run(self, tree: ast.Module) -> list[Finding]:
        self._reentrant, self._nonreentrant = _collect_lock_kinds(tree)
        self._thr_aliases, self._thr_ctors = \
            _collect_threading_imports(tree)
        self.visit(tree)
        return self.findings

    def add(self, rule: str, severity: str, node, message: str,
            hint: str = ""):
        line = getattr(node, "lineno", None)
        f = Finding(rule=rule, severity=severity, path=self.path,
                    line=line, message=message, hint=hint)
        if line is not None and line - 1 < len(self.lines):
            f = apply_suppressions(f, self.lines[line - 1])
        self.findings.append(f)

    # ------------------------------------------------- scope plumbing

    def visit_FunctionDef(self, node):
        # A def nested under a with-lock runs LATER, not under the
        # lock: fresh held/subscriber state for its body.
        held, subs = self._held, self._sub_names
        self._held, self._sub_names = [], set()
        self.generic_visit(node)
        self._held, self._sub_names = held, subs

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def visit_With(self, node: ast.With):
        chains = [c for c in (_is_lock_expr(i.context_expr)
                              for i in node.items) if c is not None]
        for chain in chains:
            if chain in self._held:
                attr = chain.rsplit(".", 1)[-1]
                # Flag only when the module PROVABLY constructs this
                # attr non-reentrant: an attr bound reentrant anywhere
                # in the module (e.g. two classes sharing the name) is
                # ambiguous, not proof.
                if (attr in self._nonreentrant
                        and attr not in self._reentrant):
                    self.add(
                        "lock-double-acquire", "error", node,
                        f"`with {chain}:` nested inside a `with "
                        f"{chain}:` block, and this module constructs "
                        f"{attr!r} NON-reentrant",
                        "a plain Lock re-acquired by its owner "
                        "deadlocks; make it a TracedRLock or hoist "
                        "the outer acquisition")
        self._held.extend(chains)
        self.generic_visit(node)
        del self._held[len(self._held) - len(chains):]

    def visit_For(self, node: ast.For):
        it = _unwrap_iter(node.iter)
        chain = _attr_chain(it)
        if chain and _callbackish(chain[-1]) \
                and isinstance(node.target, ast.Name):
            self._sub_names.add(node.target.id)
        self.generic_visit(node)

    # ----------------------------------------------------------- rules

    def visit_Call(self, node: ast.Call):
        chain = _attr_chain(node.func)
        name = chain[-1] if chain else ""

        raw = ((len(chain) == 2 and chain[0] in self._thr_aliases
                and name in _RAW_LOCK_CTORS)
               or (len(chain) == 1 and name in self._thr_ctors))
        if raw:
            norm = self.path.replace(os.sep, "/")
            allowed = any(norm.endswith(a.replace(os.sep, "/"))
                          for a in _RAW_LOCK_ALLOWLIST)
            if not allowed:
                self.add(
                    "raw-lock", "error", node,
                    f"raw threading lock (`{'.'.join(chain)}()`) "
                    "constructed in a threaded core module",
                    "use TracedLock/TracedRLock from distkeras_tpu."
                    "utils.locks so the lock-order sanitizer can see "
                    "it (free when disabled)")

        if self._held:
            is_cb = (isinstance(node.func, ast.Name)
                     and node.func.id in self._sub_names)
            if not is_cb and name and _callbackish(name):
                is_cb = True
            if is_cb:
                self.add(
                    "lock-callback", "error", node,
                    f"callback `{'.'.join(chain) or name}` invoked "
                    f"inside a `with {self._held[-1]}:` block",
                    "a subscriber may call back into this subsystem "
                    "and deadlock on the held lock (the PR-8 "
                    "slo.breach shape); collect under the lock, fire "
                    "after release, and guard the fire site with "
                    "locks.assert_unlocked()")
            reason = _blocking_reason(chain, name)
            if reason is not None:
                self.add(
                    "lock-blocking", "warn", node,
                    f"{reason} (`{'.'.join(chain) or name}` under "
                    f"`with {self._held[-1]}:`)",
                    "every thread needing this lock stalls for the "
                    "full blocking duration; move the blocking work "
                    "outside the critical section")

        self.generic_visit(node)


def lint_source_threads(source: str, path: str = "<string>"
                        ) -> list[Finding]:
    """Thread-safety lint over one source string.  Out-of-scope paths
    return no findings (the rules only apply to the threaded core)."""
    if not _in_scope(path):
        return []
    tree = ast.parse(source, filename=path)
    return _ThreadLinter(path, source).run(tree)


def lint_paths_threads(paths: Iterable[str]) -> list[Finding]:
    """Thread-safety lint over files/directories (``.py``,
    recursively; out-of-scope files are skipped)."""
    findings: list[Finding] = []
    for f in iter_py_files(paths):
        if not _in_scope(f):
            continue
        with open(f, encoding="utf-8") as fh:
            findings.extend(lint_source_threads(fh.read(), path=f))
    return findings


__all__ = ["lint_source_threads", "lint_paths_threads"]
