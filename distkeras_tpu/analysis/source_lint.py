"""Source lint: an AST rule engine with JAX-specific rules.

The IR lint sees what XLA compiles; this layer catches the hazards
that never reach a jaxpr — host work smuggled into traced functions,
synchronization in hot loops, compute at import time.  Rules:

=================  =====  ==================================================
rule id            sev    fires on
=================  =====  ==================================================
jit-wallclock      error  ``time.time/perf_counter/monotonic`` or
                          ``datetime.now`` inside a traced function (the
                          value freezes at trace time — every later step
                          replays the first call's clock)
jit-np-random      error  ``np.random`` inside a traced function (host
                          randomness freezes at trace time; use
                          ``jax.random`` with an explicit key)
hot-sync           warn   ``.block_until_ready()`` / ``jax.device_get`` in a
                          for/while body of a trainer or serving module —
                          a device sync per iteration on the hot path
import-time-jnp    warn   a ``jnp.*``/``jax.numpy`` call at module scope:
                          device compute (and backend init) at import time
mutable-default    error  mutable default argument (list/dict/set) on a
                          public function
jit-no-donate      warn   ``jax.jit(step_like_fn)`` with no
                          ``donate_argnums``: a state-carrying step that
                          copies its carry every round
axis-name          error  a mesh-axis string in ``P(...)`` or an
                          ``axis_name=`` argument that is not one of the
                          canonical ``parallel.mesh.AXES`` (typos silently
                          replicate)
loop-jit           warn   ``jax.jit(...)`` lexically inside a for/while
                          body — a fresh jit wrapper (and cache entry) per
                          iteration
jax-free           error  any ``import jax`` (or ``from jax ...``),
                          anywhere in a module on the JIT-FREE ledger
                          (``_JAX_FREE_FILES``: the live telemetry plane
                          ``obs/live.py``/``obs/slo.py`` and the offline
                          obs modules) — these run on scrape/ticker
                          threads or under obs_report.py's no-framework
                          stub loader, where touching jax would mean
                          device work on a telemetry path
=================  =====  ==================================================

Traced functions are found structurally: defs decorated with
``jax.jit``/``partial(jax.jit, ...)``, defs passed by name to
``jax.jit`` / ``shard_map`` / ``jax.lax.scan`` / ``jax.vmap`` /
``jax.grad`` / ``jax.value_and_grad`` / ``jax.checkpoint`` /
``jax.remat``, and every def nested inside one.  Suppress per line
with ``# dkt: ignore[rule]`` (see findings.py).
"""

from __future__ import annotations

import ast
import os
from typing import Iterable

from distkeras_tpu.analysis.findings import Finding, apply_suppressions
from distkeras_tpu.parallel.mesh import AXES

_TRACING_ENTRYPOINTS = {
    "jit", "scan", "shard_map", "vmap", "pmap", "grad",
    "value_and_grad", "checkpoint", "remat", "while_loop", "fori_loop",
    "cond", "switch", "custom_jvp", "custom_vjp",
}
_WALLCLOCK = {("time", "time"), ("time", "perf_counter"),
              ("time", "monotonic"), ("time", "process_time"),
              ("datetime", "now"), ("datetime", "utcnow")}
_SYNC_CALLS = {"block_until_ready", "device_get"}
_HOT_PATH_DIRS = (os.path.join("distkeras_tpu", "trainers"),
                  os.path.join("distkeras_tpu", "serving"))
_HOT_PATH_FILES = ("serving.py",)  # pre-split path; tests still use it
_STEP_NAME_HINT = ("step", "train", "update")
# The JIT-FREE ledger: modules that must never import jax, even
# lazily — the live telemetry plane (scrape/SLO threads must not be
# able to trigger device work or compilation), the offline obs
# modules (obs_report.py imports them through a no-framework stub
# loader on hosts with no jax installed), the lock sanitizer
# (utils/locks.py feeds the obs metrics registry and is imported by
# every module above), and — round 13 — the fleet router plane
# (serving/router.py + serving/residency.py: routing is host
# bookkeeping and HTTP; a router process must never be able to
# compile a program — the serving_router compile session pins the
# dynamic half of that claim).
_JAX_FREE_FILES = tuple(
    os.path.join("distkeras_tpu", "obs", f)
    for f in ("live.py", "slo.py", "metrics.py", "trace.py",
              "report.py")) + (
    os.path.join("distkeras_tpu", "utils", "locks.py"),
    os.path.join("distkeras_tpu", "serving", "router.py"),
    os.path.join("distkeras_tpu", "serving", "residency.py"),
    # Round 19: the autoscaling control plane and its trace-replay
    # load driver — scaling policy and load generation are host
    # bookkeeping; neither may ever compile a program.
    os.path.join("distkeras_tpu", "serving", "autoscale.py"),
    os.path.join("distkeras_tpu", "serving", "traffic.py"))


def _attr_chain(node) -> list[str]:
    """``a.b.c`` -> ["a", "b", "c"]; non-chains -> []."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return []


def _call_name(call: ast.Call) -> str:
    chain = _attr_chain(call.func)
    return chain[-1] if chain else ""


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, source: str):
        self.path = path
        self.lines = source.splitlines()
        self.findings: list[Finding] = []
        self.traced: set[ast.AST] = set()
        self._parents: dict[ast.AST, ast.AST] = {}

    # ---------------------------------------------------------- plumbing

    def run(self, tree: ast.Module) -> list[Finding]:
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self._parents[child] = node
        self._collect_traced(tree)
        self.visit(tree)
        return self.findings

    def add(self, rule: str, severity: str, node: ast.AST, message: str,
            hint: str = ""):
        line = getattr(node, "lineno", None)
        f = Finding(rule=rule, severity=severity, path=self.path,
                    line=line, message=message, hint=hint)
        if line is not None and line - 1 < len(self.lines):
            f = apply_suppressions(f, self.lines[line - 1])
        self.findings.append(f)

    def _enclosing_defs(self, node) -> Iterable[ast.AST]:
        cur = node
        while cur in self._parents:
            cur = self._parents[cur]
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                yield cur

    def _in_traced(self, node) -> bool:
        return any(d in self.traced for d in self._enclosing_defs(node))

    def _in_loop(self, node) -> bool:
        cur = node
        while cur in self._parents:
            parent = self._parents[cur]
            if isinstance(parent, (ast.For, ast.While)) and (
                    cur in parent.body or cur in parent.orelse):
                return True
            if isinstance(parent, (ast.FunctionDef,
                                   ast.AsyncFunctionDef, ast.Lambda)):
                return False
            cur = parent
        return False

    def _collect_traced(self, tree: ast.Module):
        defs: dict[str, list[ast.AST]] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append(node)
        roots: set[ast.AST] = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    target = dec.func if isinstance(dec, ast.Call) else dec
                    name = (_attr_chain(target) or [""])[-1]
                    if name in ("jit", "partial"):
                        names = {name} | {
                            (_attr_chain(a) or [""])[-1]
                            for a in getattr(dec, "args", [])}
                        if "jit" in names:
                            roots.add(node)
            if isinstance(node, ast.Call) and (
                    _call_name(node) in _TRACING_ENTRYPOINTS):
                for arg in list(node.args) + [k.value for k in
                                              node.keywords]:
                    if isinstance(arg, ast.Lambda):
                        roots.add(arg)
                    elif isinstance(arg, ast.Name):
                        roots.update(defs.get(arg.id, ()))
        # A def nested inside a traced def is traced too.
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                if node in roots or any(d in roots
                                        for d in self._enclosing_defs(node)):
                    self.traced.add(node)

    # ------------------------------------------------------------- rules

    def visit_Call(self, node: ast.Call):
        chain = _attr_chain(node.func)
        name = chain[-1] if chain else ""

        if self._in_traced(node):
            if len(chain) >= 2 and (chain[-2], name) in _WALLCLOCK:
                self.add("jit-wallclock", "error", node,
                         f"wall-clock call `{'.'.join(chain)}` inside a "
                         "traced function",
                         "the value is baked in at trace time; pass "
                         "host timestamps in as arguments")
            if "random" in chain and chain[0] in ("np", "numpy"):
                self.add("jit-np-random", "error", node,
                         f"host RNG `{'.'.join(chain)}` inside a traced "
                         "function",
                         "the draw happens once at trace time; use "
                         "jax.random with an explicit key argument")

        if name in _SYNC_CALLS and self._hot_path() and self._in_loop(node):
            self.add("hot-sync", "warn", node,
                     f"`{name}` inside a loop on a trainer/serving hot "
                     "path",
                     "a device sync per iteration serializes dispatch; "
                     "sync once after the loop, or justify with a "
                     "dkt: ignore")

        if name == "jit" and chain[:1] in (["jax"], ["jit"]):
            if self._in_loop(node):
                self.add("loop-jit", "warn", node,
                         "jax.jit called inside a loop body",
                         "each iteration builds a fresh jit wrapper; "
                         "hoist the jit out of the loop and reuse it")
            kw = {k.arg for k in node.keywords}
            target = node.args[0] if node.args else None
            tname = ""
            if isinstance(target, (ast.Name, ast.Attribute)):
                tname = (_attr_chain(target) or [""])[-1]
            if (tname and any(h in tname.lower() for h in _STEP_NAME_HINT)
                    and "donate_argnums" not in kw
                    and "donate_argnames" not in kw):
                self.add("jit-no-donate", "warn", node,
                         f"jax.jit({tname}) without donate_argnums",
                         "a state-carrying step that does not donate "
                         "its carry holds two copies of the state "
                         "alive every round; donate the carry argument")

        if name in ("P", "PartitionSpec"):
            for arg in node.args:
                self._check_axis_value(arg)
        for k in node.keywords:
            if k.arg in ("axis_name", "axis") and isinstance(
                    k.value, ast.Constant) and isinstance(
                        k.value.value, str):
                self._check_axis_value(k.value)

        self.generic_visit(node)

    def _check_axis_value(self, node):
        values = [node]
        if isinstance(node, (ast.Tuple, ast.List)):
            values = list(node.elts)
        for v in values:
            if (isinstance(v, ast.Constant) and isinstance(v.value, str)
                    and v.value not in AXES):
                self.add("axis-name", "error", v,
                         f"axis name {v.value!r} is not one of the "
                         f"canonical mesh axes {AXES}",
                         "a typo here silently replicates instead of "
                         "sharding; use parallel.mesh.AXES names")

    def _hot_path(self) -> bool:
        norm = self.path.replace(os.sep, "/")
        return (any(d.replace(os.sep, "/") in norm
                    for d in _HOT_PATH_DIRS)
                or any(norm.endswith(f) for f in _HOT_PATH_FILES))

    def _jax_free(self) -> bool:
        norm = self.path.replace(os.sep, "/")
        return any(norm.endswith(f.replace(os.sep, "/"))
                   for f in _JAX_FREE_FILES)

    def _check_jax_free_import(self, node, modules) -> None:
        if not self._jax_free():
            return
        for mod in modules:
            root = (mod or "").split(".")[0]
            if root == "jax":
                self.add("jax-free", "error", node,
                         f"`{mod}` imported in a jit-free module "
                         f"({os.path.basename(self.path)})",
                         "the live telemetry plane and the offline "
                         "obs modules must never touch jax — a "
                         "scrape or report must not be able to "
                         "trigger device work; move the dependency "
                         "out or read the data through the registry/"
                         "trace instead")
                return

    def visit_Import(self, node: ast.Import):
        self._check_jax_free_import(node, [a.name for a in node.names])
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom):
        self._check_jax_free_import(node, [node.module])
        self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef):
        if not node.name.startswith("_"):
            for default in (node.args.defaults
                            + [d for d in node.args.kw_defaults if d]):
                bad = isinstance(default, (ast.List, ast.Dict, ast.Set))
                if (isinstance(default, ast.Call)
                        and _call_name(default) in ("list", "dict", "set")):
                    bad = True
                if bad:
                    self.add("mutable-default", "error", default,
                             f"mutable default argument on public "
                             f"function `{node.name}`",
                             "the default is shared across calls; "
                             "default to None and build inside")
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Module(self, node: ast.Module):
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Import,
                                 ast.ImportFrom)):
                continue
            for call in ast.walk(stmt):
                if not isinstance(call, ast.Call):
                    continue
                # Skip calls nested inside defs/lambdas under this stmt
                # (e.g. a module-level dict of lambdas is lazy).
                if any(isinstance(d, (ast.FunctionDef, ast.Lambda))
                       for d in self._enclosing_defs(call)):
                    continue
                chain = _attr_chain(call.func)
                if chain[:1] == ["jnp"] or chain[:2] == ["jax", "numpy"]:
                    self.add("import-time-jnp", "warn", call,
                             f"`{'.'.join(chain)}` call at module "
                             "import time",
                             "device compute (and backend init) on "
                             "import; build constants lazily or as "
                             "plain numpy")
        self.generic_visit(node)


def lint_source(source: str, path: str = "<string>") -> list[Finding]:
    """Lint one python source string."""
    tree = ast.parse(source, filename=path)
    return _Linter(path, source).run(tree)


def iter_py_files(paths: Iterable[str]) -> list[str]:
    """Expand files/directories into the sorted ``.py`` file list both
    lint layers walk (``__pycache__`` skipped) — ONE walker, so
    file-selection fixes cannot drift between them."""
    files: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, names in os.walk(p):
                if "__pycache__" in root:
                    continue
                files.extend(os.path.join(root, n) for n in sorted(names)
                             if n.endswith(".py"))
        else:
            files.append(p)
    return sorted(files)


def lint_paths(paths: Iterable[str]) -> list[Finding]:
    """Lint files/directories (``.py`` files, recursively)."""
    findings: list[Finding] = []
    for f in iter_py_files(paths):
        with open(f, encoding="utf-8") as fh:
            findings.extend(lint_source(fh.read(), path=f))
    return findings


__all__ = ["lint_source", "lint_paths", "iter_py_files"]
