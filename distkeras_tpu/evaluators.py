"""Evaluators (reference parity: distkeras/evaluators.py).

``evaluate(dataset) -> float`` over named columns, mirroring the
reference's ``AccuracyEvaluator`` that compared a label column with a
prediction-index column on a Spark DataFrame.
"""

from __future__ import annotations

import numpy as np

from distkeras_tpu.data.dataset import Dataset


class Evaluator:
    def evaluate(self, dataset: Dataset) -> float:  # pragma: no cover
        raise NotImplementedError


class AccuracyEvaluator(Evaluator):
    """Fraction of rows where prediction index equals the label.

    Reference parity: distkeras/evaluators.py::AccuracyEvaluator.
    Accepts either an index column (from LabelIndexTransformer) or a raw
    prediction-vector column (argmaxed on the fly).
    """

    def __init__(self, prediction_col: str = "prediction_index",
                 label_col: str = "label"):
        self.prediction_col = prediction_col
        self.label_col = label_col

    def evaluate(self, dataset: Dataset) -> float:
        preds = dataset[self.prediction_col]
        if preds.ndim > 1:
            preds = np.argmax(preds, axis=-1)
        labels = dataset[self.label_col]
        if labels.ndim > 1:  # one-hot labels
            labels = np.argmax(labels, axis=-1)
        return float(np.mean(preds.astype(np.int64) == labels.astype(np.int64)))


class PerplexityEvaluator(Evaluator):
    """Held-out perplexity of a transformer LM over token rows.

    The LM-family member of the evaluator API (the reference's
    evaluators only cover classification, distkeras/evaluators.py) —
    the same quantity LMTrainer's ``eval_every`` tracks mid-training,
    packaged standalone: one jitted batched NLL, fed in ``batch_size``
    chunks (a remainder of up to ``batch_size - 1`` rows is dropped for
    static shapes), ``exp(mean NLL)`` out.  MoE aux loss is excluded —
    the router penalty is a training device, not model quality.
    """

    def __init__(self, params, cfg, batch_size: int = 8,
                 tokens_col: str = "tokens"):
        import jax

        from distkeras_tpu.models import transformer as tfm

        self.params = params
        self.cfg = cfg
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        self.batch_size = batch_size
        self.tokens_col = tokens_col
        # Jitted once here: a fresh lambda per evaluate() would retrace
        # and recompile the full forward on every call.
        self._nll = jax.jit(lambda p, t: tfm.lm_nll(p, t, cfg))

    def evaluate(self, dataset) -> float:
        from distkeras_tpu.utils.misc import nll_to_perplexity

        tokens = (dataset if isinstance(dataset, np.ndarray)
                  else dataset[self.tokens_col])
        if tokens.ndim != 2 or tokens.shape[1] < 2:
            raise ValueError(
                f"tokens must be [N, seq+1] with seq >= 1, got "
                f"{tokens.shape}")
        bs = self.batch_size
        n = len(tokens) - (len(tokens) % bs)
        if not n:
            raise ValueError(
                f"dataset has {len(tokens)} rows; one batch needs {bs}")
        total = 0.0
        for i in range(0, n, bs):
            chunk = np.asarray(tokens[i:i + bs], np.int32)
            total += float(self._nll(self.params, chunk))
        return nll_to_perplexity(total / (n // bs))
