"""Evaluators (reference parity: distkeras/evaluators.py).

``evaluate(dataset) -> float`` over named columns, mirroring the
reference's ``AccuracyEvaluator`` that compared a label column with a
prediction-index column on a Spark DataFrame.
"""

from __future__ import annotations

import numpy as np

from distkeras_tpu.data.dataset import Dataset


class Evaluator:
    def evaluate(self, dataset: Dataset) -> float:  # pragma: no cover
        raise NotImplementedError


class AccuracyEvaluator(Evaluator):
    """Fraction of rows where prediction index equals the label.

    Reference parity: distkeras/evaluators.py::AccuracyEvaluator.
    Accepts either an index column (from LabelIndexTransformer) or a raw
    prediction-vector column (argmaxed on the fly).
    """

    def __init__(self, prediction_col: str = "prediction_index",
                 label_col: str = "label"):
        self.prediction_col = prediction_col
        self.label_col = label_col

    def evaluate(self, dataset: Dataset) -> float:
        preds = dataset[self.prediction_col]
        if preds.ndim > 1:
            preds = np.argmax(preds, axis=-1)
        labels = dataset[self.label_col]
        if labels.ndim > 1:  # one-hot labels
            labels = np.argmax(labels, axis=-1)
        return float(np.mean(preds.astype(np.int64) == labels.astype(np.int64)))
