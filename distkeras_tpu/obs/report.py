"""Offline run reports from obs traces (the scripts/obs_report.py
library).

A trace JSONL (obs/trace.py) reconstructs into:

* **phase breakdown** — spans aggregated by name: call count, total /
  mean seconds, p50/p95/p99 of the span durations, share of the run's
  wall span;
* **latency percentiles** — every histogram series in the trace's
  final ``metrics`` record, rendered with bucket-interpolated
  p50/p95/p99 (obs/metrics.percentile_from_buckets);
* **counters/gauges** — the remaining metrics series;
* **event timeline** — point events in time order (chaos faults,
  supervisor attempts, admission rejects...).

``compare`` diffs two reports for regression triage: per-phase total /
mean deltas, histogram percentile deltas, counter deltas — the dynamic
reality the static comm/compile budgets (PR 3) cannot see.

This module is on the contract lint's consumer list
(``contract_lint.CONSUMER_FILES``): every metric-name literal it
compares against must resolve to a live producer, so a renamed
emission fails the lint here instead of silently emptying a report
section.
"""

from __future__ import annotations

import statistics

from distkeras_tpu.obs.metrics import percentile_from_buckets
from distkeras_tpu.obs.trace import read_trace


def _pct(durs: list, q: float) -> float:
    if not durs:
        return 0.0
    s = sorted(durs)
    idx = min(len(s) - 1, max(0, round(q * (len(s) - 1))))
    return s[int(idx)]


def build_report(records: list[dict]) -> dict:
    """Trace records -> plain-dict report (JSON-able)."""
    meta = next((r for r in records if r.get("kind") == "meta"), {})
    spans: dict[str, list] = {}
    events = []
    metrics = {}
    t_lo = t_hi = None
    for r in records:
        kind = r.get("kind")
        if kind == "span":
            spans.setdefault(r["name"], []).append(r)
            lo, hi = r["t0"], r["t0"] + r["dur"]
        elif kind == "event":
            events.append(r)
            lo = hi = r["t"]
        elif kind == "metrics":
            metrics = r.get("data", {})
            continue
        else:
            continue
        t_lo = lo if t_lo is None else min(t_lo, lo)
        t_hi = hi if t_hi is None else max(t_hi, hi)
    wall = (t_hi - t_lo) if t_lo is not None else 0.0

    phases = {}
    for name, recs in sorted(spans.items()):
        durs = [r["dur"] for r in recs]
        total = sum(durs)
        phases[name] = {
            "count": len(durs), "total_s": total,
            "mean_s": total / len(durs),
            "p50_s": statistics.median(durs),
            "p95_s": _pct(durs, 0.95), "p99_s": _pct(durs, 0.99),
            "share": (total / wall) if wall else 0.0,
        }

    hists, scalars = {}, {}
    for name, m in sorted(metrics.items()):
        for s in m.get("series", []):
            lab = ",".join(f"{k}={v}"
                           for k, v in sorted(s["labels"].items()))
            key = f"{name}{{{lab}}}" if lab else name
            if m.get("kind") == "histogram":
                if s.get("count"):
                    hists[key] = {
                        "count": s["count"],
                        "mean": s["sum"] / s["count"],
                        "min": s.get("min"), "max": s.get("max"),
                        "p50": percentile_from_buckets(s, 0.50),
                        "p95": percentile_from_buckets(s, 0.95),
                        "p99": percentile_from_buckets(s, 0.99),
                    }
            else:
                scalars[key] = s.get("value")

    timeline = [{"t": (e["t"] - t_lo) if t_lo is not None else e["t"],
                 "name": e["name"], "fields": e.get("fields", {})}
                for e in sorted(events, key=lambda e: e["t"])]
    return {"meta": {k: meta.get(k) for k in
                     ("run", "host", "pid", "time_unix")},
            "wall_s": wall, "phases": phases, "latency": hists,
            "scalars": scalars, "timeline": timeline}


def load_report(path: str) -> dict:
    return build_report(read_trace(path))


# --------------------------------------------------- request waterfall


def request_waterfall(records: list[dict], request_id: int) -> dict:
    """One serving request's life, reconstructed from its trace
    records (everything carrying ``request_id`` in its fields —
    round-11 per-request propagation): ``serving.submit`` ->
    ``serving.admit`` span (queue wait) -> ``serving.admit_chunk``
    spans (chunked prefill) -> ``serving.emit`` events (decode; the
    inter-token gaps) -> ``serving.finish``.

    **Routed requests** (round 13): when ``request_id`` is a
    fleet-wide router id, its ``router.route`` events name the
    replica-local ids each hop admitted under, and the waterfall
    follows them — the routing decision, any ``router.reroute`` hop,
    and every replica's engine-side stages render as ONE story (pass
    the MERGED records of all hosts' traces for a cross-process
    fleet; ``scripts/obs_report.py --request`` with several trace
    files does exactly that).

    Returns a plain dict: ``{"request_id", "found", "submit_t",
    "stages": [{"t", "name", "dur", ...}], "queue_wait_s", "ttft_s",
    "total_s", "status", "tokens", "reroutes", "gaps": {...}}`` with
    every ``t`` relative to the submit event (or the earliest record
    seen)."""
    # Follow router hops first: replica-local ids this fleet-wide id
    # was admitted under, each tagged with its replica name.
    ids: dict = {request_id: None}
    final_id = request_id
    for r in records:
        if r.get("kind") != "event" or r.get("name") != "router.route":
            continue
        f = r.get("fields") or {}
        if f.get("request_id") != request_id:
            continue
        rrid = f.get("replica_request_id")
        if rrid is not None:
            ids[rrid] = f.get("replica")
            final_id = rrid
    mine_events, mine_spans = [], []
    for r in records:
        fields = r.get("fields") or {}
        rid = fields.get("request_id")
        if rid not in ids:
            continue
        tagged = dict(r)
        if ids[rid] is not None:
            tagged["_replica"] = ids[rid]
        if r.get("kind") == "event":
            mine_events.append(tagged)
        elif r.get("kind") == "span":
            mine_spans.append(tagged)
    if not mine_events and not mine_spans:
        return {"request_id": request_id, "found": False}

    def at(r):
        return r["t"] if r.get("kind") == "event" else r["t0"]

    def tag(stage, rec):
        if rec.get("_replica") is not None:
            stage["replica"] = rec["_replica"]
        return stage

    submit = next((e for e in mine_events
                   if e["name"] in ("router.submit",
                                    "serving.submit")), None)
    t0 = at(submit) if submit else min(at(r) for r in
                                       mine_events + mine_spans)
    stages = []
    for sp in mine_spans:
        stages.append(tag({"t": sp["t0"] - t0, "name": sp["name"],
                           "dur_s": sp["dur"], **{
                               k: v for k, v in sp["fields"].items()
                               if k != "request_id"}}, sp))
    emits = sorted((e for e in mine_events
                    if e["name"] == "serving.emit"),
                   key=lambda e: e["t"])
    for e in emits:
        stages.append(tag({"t": e["t"] - t0, "name": "serving.emit",
                           "n": e["fields"].get("n"),
                           "first": e["fields"].get("first")}, e))
    # Router hops: the routing decision(s), any re-route, and the
    # disaggregated block-transfer hop render as first-class stages
    # (rounds 13 and 17).
    hops = [e for e in mine_events
            if e["name"] in ("router.route", "router.reroute",
                             "router.block_transfer",
                             "router.finish")]
    for e in hops:
        stages.append({"t": e["t"] - t0, "name": e["name"],
                       **{k: v for k, v in e["fields"].items()
                          if k != "request_id"}})
    finishes = sorted((e for e in mine_events
                       if e["name"] == "serving.finish"),
                      key=lambda e: e["t"])
    for e in finishes:
        stages.append(tag({"t": e["t"] - t0, "name": "serving.finish",
                           "status": e["fields"].get("status")}, e))
    stages.sort(key=lambda s: s["t"])

    admit = next((sp for sp in mine_spans
                  if sp["name"] == "serving.admit"), None)
    # Token/gap accounting over the FINAL hop only: a rerouted
    # request re-decodes from scratch on its new replica, and the
    # caller-visible transcript is the final hop's.
    final_emits = [e for e in emits
                   if (e["fields"].get("request_id",
                                       request_id)) == final_id] \
        if len(ids) > 1 else emits
    gaps = [b["t"] - a["t"]
            for a, b in zip(final_emits, final_emits[1:])]
    gapstats = None
    if gaps:
        s = sorted(gaps)
        gapstats = {"count": len(gaps), "p50_s": statistics.median(s),
                    "max_s": s[-1]}
    finish = finishes[-1] if finishes else None
    status = finish["fields"].get("status") if finish else None
    if status is None:
        rf = next((e for e in hops if e["name"] == "router.finish"),
                  None)
        if rf is not None:
            status = rf["fields"].get("status")
    out = {
        "request_id": request_id, "found": True,
        "submit_t": t0,
        "prompt_len": (submit or {}).get("fields", {}).get(
            "prompt_len"),
        "queue_wait_s": (admit["t0"] - t0) if admit and submit
        else None,
        "ttft_s": (emits[0]["t"] - t0) if emits and submit else None,
        "prefill_chunks": sum(1 for sp in mine_spans
                              if sp["name"] == "serving.admit_chunk"),
        "tokens": sum(e["fields"].get("n") or 0 for e in final_emits),
        "reroutes": sum(1 for e in hops
                        if e["name"] == "router.reroute"),
        "status": status,
        "total_s": (finish["t"] - t0) if finish else None,
        "gaps": gapstats,
        "stages": stages,
    }
    return out


def render_waterfall(wf: dict) -> str:
    """Human-readable waterfall for one request."""
    rid = wf.get("request_id")
    if not wf.get("found"):
        return (f"request {rid}: no records carry request_id={rid} "
                "(was the trace written with a round-11+ engine?)")
    out = [f"request {rid}  prompt_len={wf.get('prompt_len')}  "
           f"status={wf.get('status')}  "
           f"total {_fmt_s(wf.get('total_s'))}"]
    out.append(
        f"  queue wait {_fmt_s(wf.get('queue_wait_s'))}   ttft "
        f"{_fmt_s(wf.get('ttft_s'))}   prefill chunks "
        f"{wf.get('prefill_chunks')}   tokens {wf.get('tokens')}")
    if wf.get("reroutes"):
        out.append(f"  re-route hops: {wf['reroutes']} (a replica "
                   "died or drained mid-request)")
    g = wf.get("gaps")
    if g:
        out.append(f"  inter-token gaps: {g['count']}  p50 "
                   f"{_fmt_s(g['p50_s'])}  max {_fmt_s(g['max_s'])}")
    out.append("\n== waterfall ==")
    for s in wf["stages"]:
        extra = " ".join(f"{k}={v}" for k, v in s.items()
                         if k not in ("t", "name", "dur_s"))
        dur = f"  [{_fmt_s(s['dur_s'])}]" if "dur_s" in s else ""
        out.append(f"  +{s['t']:>9.4f}s  {s['name']:<24}{dur}  {extra}")
    return "\n".join(out)


# ------------------------------------------------------ multi-host merge


def merged_records(paths) -> list[dict]:
    """Raw event/span records from SEVERAL traces, wall-clock aligned
    (each trace's monotonic ``t``/``t0`` rebased through its meta
    anchor, the :func:`merge_traces` alignment) — what
    :func:`request_waterfall` consumes when one request crossed
    processes (a routed fleet request: the router's trace plus each
    replica's).  Single-trace callers can keep passing ``read_trace``
    output; the relative timing math is identical."""
    out: list[dict] = []
    for path in paths:
        records = read_trace(path)
        meta = next((r for r in records if r.get("kind") == "meta"), {})
        off = 0.0
        if meta.get("time_unix") is not None \
                and meta.get("t") is not None:
            off = meta["time_unix"] - meta["t"]
        for r in records:
            if r.get("kind") == "span":
                out.append({**r, "t0": r["t0"] + off})
            elif r.get("kind") == "event":
                out.append({**r, "t": r["t"] + off})
            else:
                out.append(r)
    return out


def merge_traces(paths) -> dict:
    """Merge per-host trace files into ONE cross-host event timeline.

    Each trace's monotonic clock has its own epoch, so records are
    aligned through the meta record's wall anchor (``time_unix`` taken
    at the same instant as monotonic ``t``): ``wall = time_unix +
    (t - meta.t)``.  Every event keeps its source run id and host, so
    a coordinated-restart session — several runs per host, one file
    per attempt — reads as one story: fault events on the dying host,
    watchdog trips on the survivors, supervisor resumes in the next
    epoch, in true wall order.  NTP caveat: cross-host ordering is as
    good as the hosts' wall clocks (exact in the single-machine
    harness).

    Returns ``{"hosts": [...], "timeline": [...], "wall_s": float}``;
    timeline entries are ``{"t", "host", "run", "name", "fields"}``
    with ``t`` relative to the earliest event."""
    runs = []
    events = []
    for path in paths:
        records = read_trace(path)
        meta = next((r for r in records if r.get("kind") == "meta"), {})
        off = 0.0
        if meta.get("time_unix") is not None and meta.get("t") is not None:
            off = meta["time_unix"] - meta["t"]
        host = meta.get("host", 0)
        run = meta.get("run")
        n = 0
        for r in records:
            if r.get("kind") != "event":
                continue
            events.append({"wall": r["t"] + off, "host": host,
                           "run": run, "name": r["name"],
                           "fields": r.get("fields", {})})
            n += 1
        runs.append({"path": path, "run": run, "host": host,
                     "events": n, "pid": meta.get("pid")})
    events.sort(key=lambda e: e["wall"])
    t0 = events[0]["wall"] if events else 0.0
    timeline = [{"t": e["wall"] - t0, "host": e["host"], "run": e["run"],
                 "name": e["name"], "fields": e["fields"]}
                for e in events]
    wall = (events[-1]["wall"] - t0) if events else 0.0
    return {"hosts": runs, "timeline": timeline, "wall_s": wall}


def render_merged(rep: dict, max_events: int = 200) -> str:
    out = [f"merged {len(rep['hosts'])} trace(s), "
           f"wall {_fmt_s(rep['wall_s'])}"]
    for h in rep["hosts"]:
        out.append(f"  host {h['host']}  run {h['run']}  "
                   f"{h['events']} event(s)  {h['path']}")
    out.append("\n== cross-host event timeline ==")
    shown = rep["timeline"][:max_events]
    for e in shown:
        fields = " ".join(f"{k}={v}" for k, v in e["fields"].items())
        out.append(f"  +{e['t']:>9.4f}s  h{e['host']}  "
                   f"{e['name']:<28}{fields}")
    if len(rep["timeline"]) > len(shown):
        out.append(f"  ... {len(rep['timeline']) - len(shown)} more "
                   "event(s)")
    return "\n".join(out)


# ------------------------------------------------------------ rendering


def _fmt_s(v) -> str:
    if v is None:
        return "-"
    if v >= 1.0:
        return f"{v:.3f}s"
    if v >= 1e-3:
        return f"{v * 1e3:.2f}ms"
    return f"{v * 1e6:.0f}us"


def _is_seconds(metric_name: str) -> bool:
    """Histogram naming convention: ``*_s`` series carry seconds (and
    render as latency); anything else renders as plain numbers."""
    return metric_name.split("{")[0].endswith("_s")


def _fmt_for(name: str):
    return _fmt_s if _is_seconds(name) else (
        lambda v: "-" if v is None else f"{v:.4g}")


def render_report(rep: dict, max_events: int = 60) -> str:
    out = [f"run {rep['meta'].get('run')}  host {rep['meta'].get('host')}"
           f"  wall {_fmt_s(rep['wall_s'])}"]
    if rep["phases"]:
        out.append("\n== phase breakdown (spans) ==")
        out.append(f"{'phase':<32}{'calls':>7}{'total':>10}{'mean':>10}"
                   f"{'p50':>10}{'p95':>10}{'p99':>10}{'share':>8}")
        for name, p in sorted(rep["phases"].items(),
                              key=lambda kv: -kv[1]["total_s"]):
            out.append(
                f"{name:<32}{p['count']:>7}{_fmt_s(p['total_s']):>10}"
                f"{_fmt_s(p['mean_s']):>10}{_fmt_s(p['p50_s']):>10}"
                f"{_fmt_s(p['p95_s']):>10}{_fmt_s(p['p99_s']):>10}"
                f"{p['share'] * 100:>7.1f}%")
    if rep["latency"]:
        out.append("\n== histograms (latency and sizes) ==")
        out.append(f"{'metric':<44}{'count':>7}{'mean':>12}{'p50':>12}"
                   f"{'p95':>12}{'p99':>12}")
        for name, h in sorted(rep["latency"].items()):
            fmt = _fmt_for(name)
            out.append(f"{name:<44}{h['count']:>7}{fmt(h['mean']):>12}"
                       f"{fmt(h['p50']):>12}{fmt(h['p95']):>12}"
                       f"{fmt(h['p99']):>12}")
    if rep["scalars"]:
        out.append("\n== counters / gauges ==")
        for name, v in sorted(rep["scalars"].items()):
            out.append(f"{name:<52}{v:>12g}")
    if rep["timeline"]:
        out.append("\n== event timeline ==")
        shown = rep["timeline"][:max_events]
        for e in shown:
            fields = " ".join(f"{k}={v}" for k, v in e["fields"].items())
            out.append(f"  +{e['t']:>9.4f}s  {e['name']:<28}{fields}")
        if len(rep["timeline"]) > len(shown):
            out.append(f"  ... {len(rep['timeline']) - len(shown)} more "
                       "event(s)")
    return "\n".join(out)


def _delta(old, new) -> str:
    if old is None or new is None:
        return "-"
    if not old:
        return "new" if new else "0"
    return f"{(new - old) / old * 100:+.1f}%"


def render_compare(base: dict, new: dict) -> str:
    """Human-readable regression diff: ``new`` against ``base``."""
    out = [f"compare: base run {base['meta'].get('run')} -> "
           f"new run {new['meta'].get('run')}",
           f"wall {_fmt_s(base['wall_s'])} -> {_fmt_s(new['wall_s'])} "
           f"({_delta(base['wall_s'], new['wall_s'])})"]
    names = sorted(set(base["phases"]) | set(new["phases"]))
    if names:
        out.append("\n== phases: total (mean) base -> new ==")
        for n in names:
            b, w = base["phases"].get(n), new["phases"].get(n)
            if b is None:
                out.append(f"{n:<32} ADDED    total {_fmt_s(w['total_s'])}")
            elif w is None:
                out.append(f"{n:<32} REMOVED  was {_fmt_s(b['total_s'])}")
            else:
                out.append(
                    f"{n:<32}{_fmt_s(b['total_s']):>10} ->"
                    f"{_fmt_s(w['total_s']):>10} "
                    f"({_delta(b['total_s'], w['total_s']):>7})   mean "
                    f"{_fmt_s(b['mean_s'])} -> {_fmt_s(w['mean_s'])} "
                    f"({_delta(b['mean_s'], w['mean_s'])})")
    names = sorted(set(base["latency"]) | set(new["latency"]))
    if names:
        out.append("\n== histograms: p50 / p95 / p99 base -> new ==")
        for n in names:
            b, w = base["latency"].get(n), new["latency"].get(n)
            if b is None or w is None:
                out.append(f"{n:<44} {'ADDED' if b is None else 'REMOVED'}")
                continue
            fmt = _fmt_for(n)
            out.append(
                f"{n:<44}"
                f"p50 {fmt(b['p50'])}->{fmt(w['p50'])} "
                f"({_delta(b['p50'], w['p50'])})  "
                f"p95 {fmt(b['p95'])}->{fmt(w['p95'])} "
                f"({_delta(b['p95'], w['p95'])})  "
                f"p99 {fmt(b['p99'])}->{fmt(w['p99'])} "
                f"({_delta(b['p99'], w['p99'])})")
    names = sorted(set(base["scalars"]) | set(new["scalars"]))
    if names:
        out.append("\n== counters / gauges base -> new ==")
        for n in names:
            b = base["scalars"].get(n)
            w = new["scalars"].get(n)
            out.append(f"{n:<52}{(b if b is not None else '-'):>10} -> "
                       f"{(w if w is not None else '-'):>10}")
    return "\n".join(out)


__all__ = ["build_report", "load_report", "render_report",
           "render_compare", "merge_traces", "merged_records",
           "render_merged", "request_waterfall", "render_waterfall"]
