"""Rolling-window SLO engine over the metrics registry.

The live half of the latency story: the registry's histograms are
*cumulative* (counts since the session started), which is the right
wire format for Prometheus but the wrong signal for an operator or an
autoscaler — "p99 since boot" hides a spike that started a minute ago.
This module runs a background **ticker** that snapshots every tracked
histogram, keeps a short ring of timestamped snapshots, and diffs the
newest against the one a window ago to produce **time-windowed
percentile gauges**::

    slo.windowed{metric="serving.request_s", q="p99"}  0.041

for the default watch list (TTFT / TPOT / `serving.request_s` /
`serving.queue_wait_s` / `train.step_s`) plus any metric named by a
rule.  Declarative :class:`SloRule`\\ s are evaluated on the same tick:

    SloRule("serving.request_s", percentile=0.99,
            threshold=0.250, window_s=30.0)

A rule whose windowed percentile crosses its threshold **breaches**:
one `slo.breach` obs event + a `slo.breaches{metric}` counter
increment on the ok->breach transition (edge-triggered — a sustained
breach is one event, re-armed when the window recovers), and every
subscriber callback fires with ``(rule, value)``.  The callback is the
quantitative load/latency signal the rest of the stack can consume —
e.g. an elastic-serving driver stepping lane tiers, or a
``ClusterSupervisor`` health policy (docs/observability.md has wiring
examples).

Guaranteed jit-free: this module never imports jax (pinned by the
source lint's ``jax-free`` rule) and the ticker only reads registry
snapshots — running it adds ZERO compiled programs
(``scripts/check_compile_counts.py`` session ``obs_live``).
"""

from __future__ import annotations

import dataclasses
import threading
import time

from distkeras_tpu.obs.metrics import windowed_percentiles
from distkeras_tpu.utils.locks import TracedLock, assert_unlocked

# Histograms the ticker windows even without a rule naming them — the
# serving fast path's user-facing latencies plus the training step.
DEFAULT_SLO_METRICS = ("serving.ttft_s", "serving.tpot_s",
                       "serving.request_s", "serving.queue_wait_s",
                       "train.step_s")


@dataclasses.dataclass(frozen=True)
class SloRule:
    """One declarative objective: "the ``percentile`` of ``metric``
    over the trailing ``window_s`` seconds stays under ``threshold``".

    ``metric`` names a registry histogram (all label sets of the name
    are aggregated — an SLO is about the workload, not one series);
    ``percentile`` is a quantile in (0, 1]; ``threshold`` is in the
    metric's own unit (seconds for the ``*_s`` conventions).

    ``replica`` (round 14): the serving-fleet replica this rule is
    scoped to.  Pure label plumbing — evaluation is unchanged — but
    every ``slo.breach`` event and subscriber callback for the rule
    carries it, so a fleet-level consumer
    (:meth:`~distkeras_tpu.serving.router.Router.breach_demoter`) can
    demote the RIGHT replica without a hand-built closure per replica;
    :meth:`Router.slo_rules` stamps one copy per attached replica."""

    metric: str
    percentile: float
    threshold: float
    window_s: float = 30.0
    replica: str | None = None

    def __post_init__(self):
        if not 0.0 < self.percentile <= 1.0:
            raise ValueError(
                f"percentile must be in (0, 1], got {self.percentile}")
        if self.threshold <= 0:
            raise ValueError(
                f"threshold must be > 0, got {self.threshold}")
        if self.window_s <= 0:
            raise ValueError(
                f"window_s must be > 0, got {self.window_s}")

    @property
    def q_label(self) -> str:
        return f"p{int(round(self.percentile * 100))}"


class SloEngine:
    """The rolling-window ticker (see module docstring).

    ``registry``: the live :class:`~distkeras_tpu.obs.metrics.
    MetricsRegistry` to window; ``rules``: :class:`SloRule`\\ s;
    ``emit``: an event sink ``emit(name, **fields)`` (the obs session
    passes its trace-event hook) — optional; breaches always reach the
    counter and the subscribers.  ``clock`` is injectable so tests
    tick deterministically; :meth:`tick` is public for the same
    reason (the background thread just calls it every ``tick_s``).

    The engine's emissions (``slo.breaches``/``slo.windowed``/the
    ``slo.breach`` event) and the metric names its rules reference are
    both sides of a contract-lint check: the shapes are pinned in
    ``scripts/obs_schema.json`` and every referenced name must resolve
    to a live producer — the autoscaler's input contract.
    """

    def __init__(self, registry, rules=(), *, tick_s: float = 1.0,
                 metrics=None, percentiles=(0.5, 0.95, 0.99),
                 emit=None, clock=time.monotonic):
        if tick_s <= 0:
            raise ValueError(f"tick_s must be > 0, got {tick_s}")
        self.registry = registry
        self.rules = tuple(rules)
        self.tick_s = tick_s
        self.percentiles = tuple(percentiles)
        self._emit = emit
        self._clock = clock
        watch = (DEFAULT_SLO_METRICS if metrics is None
                 else tuple(metrics))
        self.metrics = tuple(dict.fromkeys(
            list(watch) + [r.metric for r in self.rules]))
        # Ring of (t, {metric: aggregated-series snapshot}); pruned to
        # the longest window any consumer needs.
        self._ring: list[tuple[float, dict]] = []
        self._keep_s = max([r.window_s for r in self.rules]
                           + [30.0]) * 2.0
        self._breached: dict[int, bool] = {}
        self._subscribers: list = []
        # Guards the ring/breach state and the subscriber list; the
        # registry lock nests INSIDE it (_aggregate -> snapshot).
        # Subscriber callbacks always fire with it RELEASED (the PR-8
        # deadlock regression; locks.assert_unlocked pins it).
        self._lock = TracedLock("obs.slo")
        self._stop = threading.Event()
        self._thread = None
        self.last_values: dict[tuple[str, str], float] = {}

    # ---------------------------------------------------------- wiring

    def subscribe(self, fn) -> None:
        """Register ``fn(rule, value)`` to fire on every ok->breach
        transition.  Called from the ticker thread with the engine
        lock RELEASED, so the callback may query the engine
        (``windowed()``) or block — it only delays later ticks, never
        deadlocks them.  Registration itself takes the lock, so a
        subscribe racing a tick is ordered, not torn."""
        with self._lock:
            self._subscribers.append(fn)

    # ------------------------------------------------------------ ticks

    def _aggregate(self) -> dict:
        """One cumulative snapshot per watched metric, label sets
        summed (bucket edges are shared per instrument, so counts add
        elementwise)."""
        snap = self.registry.snapshot()
        out = {}
        for name in self.metrics:
            m = snap.get(name)
            if m is None or m.get("kind") != "histogram":
                continue
            agg = None
            for s in m["series"]:
                if agg is None:
                    agg = {"count": s["count"], "sum": s["sum"],
                           "buckets": list(s["buckets"]),
                           "counts": list(s["counts"])}
                else:
                    agg["count"] += s["count"]
                    agg["sum"] += s["sum"]
                    agg["counts"] = [a + b for a, b in
                                     zip(agg["counts"], s["counts"])]
            if agg is not None:
                out[name] = agg
        return out

    def _baseline(self, now: float, window_s: float) -> dict | None:
        """The newest ring entry at least ``window_s`` old (the window
        start).  None when the engine is younger than one window:
        everything observed so far IS inside the window, so the diff
        degenerates to the cumulative view — correct, not a fallback."""
        base = None
        for t, snap in self._ring:
            if now - t >= window_s:
                base = snap
            else:
                break
        return base

    def windowed(self, metric: str, percentile: float,
                 window_s: float) -> float | None:
        """The current windowed percentile for ``metric`` (None when
        the window saw no observations)."""
        with self._lock:
            now = self._clock()
            cur = self._aggregate().get(metric)
            if cur is None:
                return None
            base = self._baseline(now, window_s)
            base = None if base is None else base.get(metric)
            win = windowed_percentiles(cur, base, qs=(percentile,))
            if win is None:
                return None
            return win[f"p{int(round(percentile * 100))}"]

    def tick(self) -> dict:
        """One evaluation pass: window every watched metric into
        ``slo.windowed`` gauges, evaluate every rule, emit breaches.
        Returns ``{(metric, q): value}`` for the default window (the
        gauges' view) — public so tests and the compile guard can
        drive the engine deterministically.

        Breach events and subscriber callbacks fire AFTER the engine
        lock is released, so a subscriber may freely call back into
        the engine (``windowed()``) or block without wedging the
        ticker."""
        with self._lock:
            values, fired = self._tick_locked()
            subscribers = list(self._subscribers)
        if fired:
            # The lock-sanitizer guard: breach events and subscriber
            # callbacks MUST fire with the engine lock released (the
            # PR-8 subscriber-calls-windowed() deadlock).
            assert_unlocked("slo.breach subscribers")
        for rule, value in fired:
            if self._emit is not None:
                labels = ({"replica": rule.replica}
                          if rule.replica is not None else {})
                self._emit("slo.breach", metric=rule.metric,
                           q=rule.q_label, value=value,
                           threshold=rule.threshold,
                           window_s=rule.window_s, **labels)
            for fn in subscribers:
                try:
                    fn(rule, value)
                except Exception:  # noqa: BLE001 — a subscriber
                    pass           # must not kill the ticker
        return values

    def _tick_locked(self) -> tuple:
        now = self._clock()
        cur = self._aggregate()
        # Gauges: the default 30s window over every watched metric.
        gauge = self.registry.gauge(
            "slo.windowed", "rolling-window percentile (SLO engine)")
        values: dict = {}
        base_default = self._baseline(now, 30.0)
        for name, agg in cur.items():
            old = None if base_default is None \
                else base_default.get(name)
            win = windowed_percentiles(agg, old, qs=self.percentiles)
            if win is None:
                continue
            for q in self.percentiles:
                lab = f"p{int(round(q * 100))}"
                values[(name, lab)] = win[lab]
                gauge.set(win[lab], metric=name, q=lab)
        # Rules: each on ITS window.  Breach notifications are only
        # COLLECTED here; tick() fires them outside the lock.
        fired = []
        for i, rule in enumerate(self.rules):
            base = self._baseline(now, rule.window_s)
            old = None if base is None else base.get(rule.metric)
            agg = cur.get(rule.metric)
            value = None
            if agg is not None:
                win = windowed_percentiles(agg, old,
                                           qs=(rule.percentile,))
                if win is not None:
                    value = win[rule.q_label]
            breached = value is not None and value > rule.threshold
            if breached and not self._breached.get(i):
                labels = ({"replica": rule.replica}
                          if rule.replica is not None else {})
                self.registry.counter(
                    "slo.breaches",
                    "ok->breach transitions per SLO rule").inc(
                        metric=rule.metric, q=rule.q_label, **labels)
                fired.append((rule, value))
            self._breached[i] = breached
        # Ring maintenance: append, prune beyond the longest window.
        self._ring.append((now, cur))
        cutoff = now - self._keep_s
        while len(self._ring) > 1 and self._ring[1][0] <= cutoff:
            self._ring.pop(0)
        self.last_values = values
        return values, fired

    # ---------------------------------------------------------- thread

    def start(self) -> "SloEngine":
        if self._thread is not None:
            raise RuntimeError("SLO engine already started")

        def run():
            while not self._stop.wait(self.tick_s):
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 — a torn tick must
                    pass           # not kill telemetry for the run

        self._thread = threading.Thread(target=run, name="dkt-slo-tick",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


__all__ = ["SloRule", "SloEngine", "DEFAULT_SLO_METRICS"]
