"""Observability: unified metrics + structured event tracing.

The telemetry subsystem every layer records through (docs/
observability.md): trainers (``StepTimer`` phases as spans, loss/
timing gauges), serving (queue depth, rejects, deadline misses, lane
occupancy, speculative accept rate, request latency histograms),
resilience (chaos faults, Supervisor attempts/backoff, checkpoint
durations), and the data path (prefetch occupancy, h2d bytes).

Usage::

    from distkeras_tpu import obs

    with obs.session(trace_path="run.jsonl") as sess:
        trainer.train(tokens)
        engine.step()
    print(sess.registry.render_text())          # Prometheus text
    # python scripts/obs_report.py run.jsonl    # offline run report

**Disabled is the default and costs (almost) nothing.**  Every hook in
the production code calls a module function here (``obs.count`` /
``obs.gauge`` / ``obs.observe`` / ``obs.event`` / ``obs.span``) whose
first statement is ``if _ACTIVE is None: return`` — one module-attr
load and an ``is`` check, the same idiom as ``resilience.chaos.probe``.
No registry, no trace file, no background thread exists until
:func:`enable` runs.  Nothing here ever reaches inside a jitted
program (no host callbacks — pinned by the graph lint's
``host-callback`` rule over the real step programs, tests/test_obs.py),
so enabling telemetry cannot change compile counts or comm budgets.

One session is active at a time (like a chaos ``FaultPlan``: a
telemetry stream must be read off one sink, not two interleaved ones).
"""

from __future__ import annotations

import contextlib

from distkeras_tpu.obs.metrics import (Counter, Gauge, Histogram,
                                        MetricsRegistry,
                                        DEFAULT_TIME_BUCKETS,
                                        percentile_from_buckets)
from distkeras_tpu.obs.trace import EventTrace, read_trace
from distkeras_tpu.obs.slo import SloEngine, SloRule
from distkeras_tpu.obs.live import HeartbeatHealth, TelemetryServer

_ACTIVE = None


class ObsSession:
    """One enabled telemetry window: a :class:`MetricsRegistry` plus an
    optional :class:`EventTrace` (``trace_path=None`` = metrics only).

    On close the registry snapshot is appended to the trace as its
    final ``metrics`` record, so the JSONL file alone is enough for
    ``scripts/obs_report.py`` (latency percentiles included).

    **Live telemetry plane** (round 11): ``serve_port=`` starts a
    :class:`~distkeras_tpu.obs.live.TelemetryServer` on the session's
    registry (``/metrics``, ``/snapshot.json``, ``/healthz``,
    ``/trace/tail``, ``/metrics/cluster`` — port 0 = ephemeral, read
    ``sess.server.port``); ``slo_rules=`` starts the rolling-window
    :class:`~distkeras_tpu.obs.slo.SloEngine` ticker (also started,
    rule-less, whenever the server runs, so ``/metrics`` always
    carries the ``slo_windowed`` gauges).  Both are stdlib daemon
    threads that only READ the registry: enabling them cannot touch
    compile counts (the ``obs_live`` compile session pins it).
    """

    def __init__(self, trace_path: str | None = None,
                 run_id: str | None = None,
                 serve_port: int | None = None,
                 serve_host: str = "127.0.0.1", health=None,
                 slo_rules=None, slo_tick_s: float = 1.0,
                 residency=None):
        self.registry = MetricsRegistry()
        self.trace = (EventTrace(trace_path, run_id=run_id)
                      if trace_path else None)
        self.run_id = self.trace.run_id if self.trace else run_id
        self.slo = None
        self.server = None
        try:
            if slo_rules is not None or serve_port is not None:
                self.slo = SloEngine(
                    self.registry, slo_rules or (), tick_s=slo_tick_s,
                    emit=self.trace.event if self.trace else None
                ).start()
            if serve_port is not None:
                self.server = TelemetryServer(
                    self.registry, port=serve_port, bind=serve_host,
                    trace_path=trace_path, health=health,
                    residency=residency).start()
        except BaseException:
            # A failed live-plane start (e.g. the fixed serve_port is
            # already bound) must not leak the already-running ticker
            # thread or the open trace file: enable() re-raises with
            # _ACTIVE still None, so nothing else could clean up.
            self.close()
            raise

    def close(self) -> None:
        if self.server is not None:
            self.server.stop()
        if self.slo is not None:
            self.slo.stop()
        if self.trace is not None:
            self.trace.metrics(self.registry.snapshot())
            self.trace.close()


def enable(trace_path: str | None = None, run_id: str | None = None,
           **live_kw) -> ObsSession:
    """Activate telemetry; returns the session.  Pair with
    :func:`disable`, or use :func:`session` for scoped enablement.
    ``live_kw`` (``serve_port=`` / ``serve_host=`` / ``health=`` /
    ``slo_rules=`` / ``slo_tick_s=``) opt into the live telemetry
    plane — see :class:`ObsSession`."""
    global _ACTIVE
    if _ACTIVE is not None:
        raise RuntimeError(
            "an obs session is already active; telemetry sessions do "
            "not nest (disable() the current one first)")
    _ACTIVE = ObsSession(trace_path=trace_path, run_id=run_id,
                         **live_kw)
    return _ACTIVE


def disable() -> None:
    """Deactivate and close the current session (no-op when none)."""
    global _ACTIVE
    sess, _ACTIVE = _ACTIVE, None
    if sess is not None:
        sess.close()


@contextlib.contextmanager
def session(trace_path: str | None = None, run_id: str | None = None,
            **live_kw):
    """``with obs.session("run.jsonl") as sess: ...`` (pass
    ``serve_port=``/``slo_rules=`` for the live telemetry plane)."""
    sess = enable(trace_path=trace_path, run_id=run_id, **live_kw)
    try:
        yield sess
    finally:
        disable()


def active() -> ObsSession | None:
    """The enabled session, or None — production hooks use the module
    functions below instead of checking this directly."""
    return _ACTIVE


# --------------------------------------------------------------- hooks
#
# The functions the instrumented layers call.  Each one is a no-op
# (one attribute load + `is` check) when telemetry is disabled.


# Each hook binds _ACTIVE to a local ONCE: a concurrent disable()
# (bench_suite's per-config teardown, while a daemon Prefetcher thread
# is mid-record) must find a hook working on the session it sampled,
# never a half-observed None.


def count(name: str, n: float = 1.0, **labels) -> None:
    """Increment a counter (created on first use)."""
    sess = _ACTIVE
    if sess is None:
        return
    sess.registry.counter(name).inc(n, **labels)


def gauge(name: str, value: float, **labels) -> None:
    """Set a gauge (created on first use)."""
    sess = _ACTIVE
    if sess is None:
        return
    sess.registry.gauge(name).set(value, **labels)


def observe(name: str, value: float, buckets=None, **labels) -> None:
    """Record one histogram observation (default latency buckets)."""
    sess = _ACTIVE
    if sess is None:
        return
    h = (sess.registry.histogram(name) if buckets is None
         else sess.registry.histogram(name, buckets=buckets))
    h.observe(value, **labels)


def event(name: str, **fields) -> None:
    """Append a point event to the trace (no-op without a trace)."""
    sess = _ACTIVE
    if sess is None or sess.trace is None:
        return
    sess.trace.event(name, **fields)


_NULL = contextlib.nullcontext()


def span(name: str, **fields):
    """Span context manager; a shared null context when disabled (no
    allocation on the disabled path)."""
    sess = _ACTIVE
    if sess is None or sess.trace is None:
        return _NULL
    return sess.trace.span(name, **fields)


__all__ = ["ObsSession", "MetricsRegistry", "Counter", "Gauge",
           "Histogram", "EventTrace", "read_trace",
           "percentile_from_buckets", "DEFAULT_TIME_BUCKETS",
           "SloRule", "SloEngine", "TelemetryServer", "HeartbeatHealth",
           "enable", "disable", "session", "active",
           "count", "gauge", "observe", "event", "span"]
