"""Live telemetry plane: the in-process HTTP scrape endpoint.

Everything PR 4 could only render *offline* — the Prometheus text
exposition, registry snapshots, the JSONL event trace — served live
while a trainer or serving engine runs, from a stdlib
``ThreadingHTTPServer`` on a daemon thread:

=====================  ==================================================
endpoint               serves
=====================  ==================================================
``/metrics``           the registry's Prometheus text exposition
``/snapshot.json``     ``MetricsRegistry.snapshot()`` as JSON
``/healthz``           heartbeat freshness (resilience/health.py):
                       200 fresh / 503 stale — the health-check a
                       router or k8s probe points at
``/trace/tail?n=N``    the last N JSONL trace records (torn-tail
                       tolerant, like ``read_trace``)
``/metrics/cluster``   every cluster host's ``/metrics`` merged, each
                       series labeled ``host="N"`` (federation)
``/residency``         the attached engine's residency digest
                       (resident stem hashes / prefix ids / live
                       load — ``residency=engine.residency``): the
                       cache-aware router's affinity ground truth
                       (round 13)
=====================  ==================================================

Started via ``obs.session(serve_port=...)`` (port 0 = ephemeral; the
bound port is ``sess.server.port``).  **Cluster federation**: when the
``DKT_CLUSTER_*`` env contract is present (the ``ClusterSupervisor``
driver sets it; resilience/cluster.py), every host's server publishes
its address as ``<DKT_CLUSTER_DIR>/telemetry/host<N>.addr`` and
``/metrics/cluster`` scrapes every published peer, so host 0's
endpoint is the one place a fleet dashboard scrapes — a killed host's
series drop out (its scrape fails, ``cluster_scrape_up{host} 0``) and
return when the coordinated restart republishes its address.

Guaranteed jit-free: this module never imports jax (source lint
``jax-free`` rule) and request handlers only read the registry /
trace file — a running server adds ZERO compiled programs
(``scripts/check_compile_counts.py`` session ``obs_live``).
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from distkeras_tpu.utils.locks import assert_unlocked


# ------------------------------------------------------------- health


class HeartbeatHealth:
    """``/healthz`` source wired to resilience/health.py beat files:
    healthy while THIS host's latest beat is younger than ``window``
    seconds (or is the terminal ``done`` beat — clean completion is
    not sickness).  A wedged heartbeat writer (the ``stall`` chaos
    kind) therefore flips the endpoint 200 -> 503 within one window,
    with no cooperation from the wedged thread."""

    def __init__(self, directory: str, host: int, window: float = 3.0,
                 clock=time.time):
        if window <= 0:
            raise ValueError(f"window must be > 0, got {window}")
        self.directory = directory
        self.host = host
        self.window = window
        self._clock = clock

    def __call__(self):
        # Late import: health.py is stdlib-only, but routing through
        # the resilience package keeps this module importable under
        # obs_report.py's no-framework stub loader.
        from distkeras_tpu.resilience.health import beat_age

        aged = beat_age(self.directory, self.host, clock=self._clock)
        if aged is None:
            return False, {"source": "heartbeat", "host": self.host,
                           "error": "no beat file"}
        age, done = aged
        ok = done or age <= self.window
        return ok, {"source": "heartbeat", "host": self.host,
                    "age_s": round(age, 3), "window_s": self.window,
                    "done": done}


def _health_from_env():
    env = os.environ
    if "DKT_CLUSTER_DIR" in env:
        return HeartbeatHealth(
            os.path.join(env["DKT_CLUSTER_DIR"], "hb"),
            host=int(env.get("DKT_CLUSTER_HOST", "0")),
            window=float(env.get("DKT_CLUSTER_WINDOW", "3.0")))
    return lambda: (True, {"source": "none"})


# --------------------------------------------------------- federation


def merge_expositions(texts: dict) -> str:
    """Merge per-host Prometheus text expositions into ONE, each
    sample labeled ``host="N"``.  ``texts``: ``{host_id: exposition
    text | None}`` (None = unreachable).  Metric families stay grouped
    (one HELP/TYPE header, then every host's samples) — the text
    format requires all lines of a family in one block.  Reachability
    itself is a series: ``cluster_scrape_up{host="N"} 0|1``."""
    helps: dict[str, str] = {}
    types: dict[str, str] = {}
    samples: dict[str, list[str]] = {}
    order: list[str] = []

    def family_of(name: str) -> str:
        if name in types:
            return name
        for suf in ("_bucket", "_sum", "_count", "_total"):
            if name.endswith(suf) and name[:-len(suf)] in types:
                return name[:-len(suf)]
        return name

    def add(family: str, line: str) -> None:
        if family not in samples:
            samples[family] = []
            order.append(family)
        samples[family].append(line)

    for host in sorted(texts):
        text = texts[host]
        if text is None:
            continue
        for line in text.splitlines():
            line = line.rstrip()
            if not line:
                continue
            if line.startswith("# HELP "):
                _, _, rest = line.partition("# HELP ")
                name = rest.split(" ", 1)[0]
                helps.setdefault(name, line)
                continue
            if line.startswith("# TYPE "):
                _, _, rest = line.partition("# TYPE ")
                name = rest.split(" ", 1)[0]
                types.setdefault(name, rest.split(" ", 2)[1]
                                 if len(rest.split(" ")) > 1 else "")
                continue
            if line.startswith("#"):
                continue
            brace = line.find("{")
            space = line.find(" ")
            if brace != -1 and (space == -1 or brace < space):
                name = line[:brace]
                rest = line[brace + 1:]
                new = f'{name}{{host="{host}",{rest}'
            else:
                name, _, value = line.partition(" ")
                new = f'{name}{{host="{host}"}} {value}'
            add(family_of(name), new)

    up = "cluster_scrape_up"
    lines = [f"# HELP {up} 1 when the host's /metrics scrape "
             "succeeded, 0 when it was unreachable",
             f"# TYPE {up} gauge"]
    for host in sorted(texts):
        ok = 0 if texts[host] is None else 1
        lines.append(f'{up}{{host="{host}"}} {ok}')
    for family in order:
        if family in helps:
            lines.append(helps[family])
        if family in types:
            lines.append(f"# TYPE {family} {types[family]}")
        lines.extend(samples[family])
    return "\n".join(lines) + "\n"


# ------------------------------------------------------------- server


class _Handler(BaseHTTPRequestHandler):
    # The telemetry wire protocol — routes/params/status codes are
    # censused by the contract lint against scripts/obs_schema.json;
    # operator-only routes (no in-repo client) are itemized in
    # contract_lint.OPERATOR_ROUTES.
    server_version = "dkt-telemetry/1.0"

    def log_message(self, *a):  # pragma: no cover — silence stderr
        pass

    def _send(self, code: int, body: str, ctype: str) -> None:
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802 — http.server API
        tel: "TelemetryServer" = self.server.telemetry
        url = urlparse(self.path)
        try:
            if url.path == "/metrics":
                self._send(200, tel.registry.render_text(),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif url.path == "/snapshot.json":
                self._send(200, json.dumps(tel.registry.snapshot(),
                                           default=str),
                           "application/json")
            elif url.path == "/healthz":
                ok, detail = tel.check_health()
                self._send(200 if ok else 503,
                           json.dumps({"ok": ok, **detail}),
                           "application/json")
            elif url.path == "/trace/tail":
                q = parse_qs(url.query)
                n = int(q.get("n", ["50"])[0])
                body = tel.trace_tail(n)
                if body is None:
                    self._send(404, "no trace attached to this "
                               "session\n", "text/plain")
                else:
                    self._send(200, body, "application/x-ndjson")
            elif url.path == "/metrics/cluster":
                self._send(200, tel.cluster_metrics(),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif url.path == "/residency":
                doc = tel.residency_doc()
                if doc is None:
                    self._send(404, "no residency source attached "
                               "to this server (pass residency= a "
                               "callable, e.g. engine.residency)\n",
                               "text/plain")
                else:
                    self._send(200, json.dumps(doc, default=str),
                               "application/json")
            else:
                self._send(404, f"unknown endpoint {url.path}\n"
                           "(try /metrics /snapshot.json /healthz "
                           "/trace/tail /metrics/cluster "
                           "/residency)\n",
                           "text/plain")
        except BrokenPipeError:  # pragma: no cover — client went away
            pass
        except Exception as e:  # noqa: BLE001 — a torn scrape must
            try:                 # not kill the serving thread
                self._send(500, f"{type(e).__name__}: {e}\n",
                           "text/plain")
            except Exception:  # pragma: no cover
                pass


class TelemetryServer:
    """The live scrape endpoint (see module docstring).

    ``registry`` is the live metrics registry; ``trace_path`` enables
    ``/trace/tail``; ``health`` is a callable ``() -> (ok, detail)``
    (or ``(ok,)``/bool), default: heartbeat freshness from the
    ``DKT_CLUSTER_*`` env when present, else always-healthy.
    ``cluster_dir``/``host_id`` opt into federation explicitly (tests;
    production rides the env contract).  ``port=0`` binds an ephemeral
    port — read ``server.port`` / ``server.url`` after :meth:`start`.

    ``advertise``: the hostname/IP peers should dial for federation —
    what the published ``.addr`` file carries, NOT necessarily the
    bind address.  Defaults to ``$DKT_TELEMETRY_ADVERTISE``, else the
    machine hostname when binding a wildcard address, else the bind
    address itself (correct for the single-machine harness; a real
    multi-machine fleet binds ``0.0.0.0`` or sets the env var —
    advertising a loopback bind to remote peers would make every peer
    dial itself).
    """

    def __init__(self, registry, *, port: int = 0,
                 bind: str = "127.0.0.1", trace_path: str | None = None,
                 health=None, cluster_dir: str | None = None,
                 host_id: int | None = None, advertise: str | None = None,
                 scrape_timeout: float = 1.0, residency=None):
        self.registry = registry
        self.trace_path = trace_path
        # ``/residency`` source (round 13): a callable returning the
        # engine's residency digest dict (``engine.residency`` — the
        # cache-aware router's affinity ground truth).  Injected as a
        # callable so this module stays jax-free: the server only
        # relays the dict.
        self._residency = residency
        self._health = health if health is not None \
            else _health_from_env()
        env = os.environ
        if cluster_dir is None and "DKT_CLUSTER_DIR" in env:
            cluster_dir = env["DKT_CLUSTER_DIR"]
        if host_id is None:
            host_id = int(env.get("DKT_CLUSTER_HOST", "0"))
        self.cluster_dir = cluster_dir
        self.host_id = host_id
        self.scrape_timeout = scrape_timeout
        self._bind = bind
        if advertise is None:
            advertise = env.get("DKT_TELEMETRY_ADVERTISE")
        if advertise is None and bind in ("", "0.0.0.0", "::"):
            import socket

            advertise = socket.gethostname()
        self.advertise = advertise if advertise is not None else bind
        self._want_port = port
        self._httpd = None
        self._thread = None
        self.port = None

    # --------------------------------------------------------- lifecycle

    @property
    def url(self) -> str:
        return f"http://{self._bind}:{self.port}"

    def start(self) -> "TelemetryServer":
        if self._httpd is not None:
            raise RuntimeError("telemetry server already started")
        self._httpd = ThreadingHTTPServer((self._bind, self._want_port),
                                          _Handler)
        self._httpd.daemon_threads = True
        self._httpd.telemetry = self
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.2},
            name="dkt-telemetry", daemon=True)
        self._thread.start()
        self._publish_addr()
        return self

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self._unpublish_addr()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # ----------------------------------------------------------- health

    def check_health(self):
        # The injected probe is user code: it must never run under a
        # sanitized lock (it may block on I/O or call back into obs).
        assert_unlocked("obs.live health probe")
        try:
            out = self._health()
        except Exception as e:  # noqa: BLE001 — a broken probe is down
            return False, {"error": f"{type(e).__name__}: {e}"}
        if isinstance(out, tuple):
            ok, detail = out
            return bool(ok), dict(detail)
        return bool(out), {}

    # ------------------------------------------------------- residency

    def residency_doc(self):
        """The attached residency source's digest, or None when no
        source is attached.  Never runs under a sanitized lock (the
        source is engine code that takes the admission lock
        itself)."""
        if self._residency is None:
            return None
        assert_unlocked("obs.live residency source")
        return self._residency()

    # ------------------------------------------------------- trace tail

    def trace_tail(self, n: int) -> str | None:
        """The last ``n`` records of the session's trace file as
        NDJSON (the same torn-tail tolerance as ``read_trace``: a
        half-written final line from the live writer is dropped, not
        an error)."""
        if self.trace_path is None:
            return None
        from distkeras_tpu.obs.trace import tail_trace

        recs = tail_trace(self.trace_path, max(n, 0))
        return "".join(json.dumps(r, default=str) + "\n" for r in recs)

    # ------------------------------------------------------- federation

    def _addr_dir(self) -> str | None:
        if self.cluster_dir is None:
            return None
        return os.path.join(self.cluster_dir, "telemetry")

    def _publish_addr(self) -> None:
        d = self._addr_dir()
        if d is None:
            return
        os.makedirs(d, exist_ok=True)
        tmp = os.path.join(d, f".addr.{self.host_id}.{os.getpid()}.tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"host": self.host_id,
                       "addr": f"{self.advertise}:{self.port}",
                       "pid": os.getpid()}, f)
        os.replace(tmp, os.path.join(d, f"host{self.host_id}.addr"))

    def _unpublish_addr(self) -> None:
        d = self._addr_dir()
        if d is None:
            return
        try:
            os.remove(os.path.join(d, f"host{self.host_id}.addr"))
        except OSError:
            pass

    def peers(self) -> dict:
        """``{host_id: "ip:port"}`` for every published telemetry
        address in the cluster dir (self included)."""
        d = self._addr_dir()
        out = {}
        if d is None or not os.path.isdir(d):
            return out
        for name in sorted(os.listdir(d)):
            if not (name.startswith("host") and name.endswith(".addr")):
                continue
            try:
                with open(os.path.join(d, name), encoding="utf-8") as f:
                    rec = json.load(f)
                out[int(rec["host"])] = rec["addr"]
            except (OSError, ValueError, KeyError):
                continue  # torn publish mid-replace: skip this pass
        return out

    def _scrape_peer(self, addr: str) -> str | None:
        try:
            with urllib.request.urlopen(
                    f"http://{addr}/metrics",
                    timeout=self.scrape_timeout) as resp:
                return resp.read().decode("utf-8")
        except Exception:  # noqa: BLE001 — dead peer == absent
            return None

    def cluster_metrics(self) -> str:
        """The federated exposition: every published host's
        ``/metrics`` merged with ``host=`` labels (own registry read
        locally — no self-scrape loop).  Peers are scraped
        CONCURRENTLY, so N dead peers cost one ``scrape_timeout``
        total, not N — unreachable ones are skipped and reported via
        ``cluster_scrape_up``."""
        import concurrent.futures

        peers = self.peers()
        if not peers:
            peers = {self.host_id: f"{self.advertise}:{self.port}"}
        texts: dict = {}
        remote = {h: a for h, a in peers.items() if h != self.host_id}
        if self.host_id in peers or not remote:
            texts[self.host_id] = self.registry.render_text()
        if remote:
            with concurrent.futures.ThreadPoolExecutor(
                    max_workers=min(len(remote), 16),
                    thread_name_prefix="dkt-fed-scrape") as pool:
                futs = {h: pool.submit(self._scrape_peer, a)
                        for h, a in remote.items()}
                for h, fut in futs.items():
                    texts[h] = fut.result()
        return merge_expositions(texts)


__all__ = ["TelemetryServer", "HeartbeatHealth", "merge_expositions"]
