"""Process-wide metrics registry: counters, gauges, histograms.

The reference framework's observability is one wall-clock float
(reference: distkeras/trainers.py ``training_time``); PR 1-3 each grew
their own ad-hoc signals (Supervisor ``attempts``, ``StepTimer``
phases, chaos ``events``).  This registry is the common sink: every
subsystem records into ONE process-wide namespace, snapshot-on-demand,
cheap enough for hot loops.

Design constraints (docs/observability.md):

* **Hot-loop cheap.**  An instrument update is a dict lookup plus a
  float add under a lock that is uncontended in the single-threaded
  hot paths.  No string formatting, no IO, no allocation beyond the
  first update of a label set.  (The *disabled* path is cheaper still:
  the ``obs`` module facade answers ``_ACTIVE is None`` before any
  registry is touched — see ``obs/__init__``.)
* **Labels.**  Every instrument takes ``**labels`` (string keys, any
  scalar values); each distinct label set is its own series, keyed by
  the sorted ``(key, value)`` tuple.
* **Histograms** use *fixed bucket edges* chosen at creation (default:
  log-spaced latency edges) — cumulative bucket counts like
  Prometheus, so percentiles are estimable offline and two snapshots
  subtract cleanly.
* **Snapshot isolation.**  :meth:`MetricsRegistry.snapshot` returns
  plain dicts/lists decoupled from live state: updates after the
  snapshot never mutate it.

Exporters: :meth:`MetricsRegistry.render_text` (Prometheus text
exposition format) and the JSONL ``metrics`` record the obs session
appends to its event trace on close (obs/trace.py).
"""

from __future__ import annotations

import bisect
import re

from distkeras_tpu.utils.locks import TracedLock

# Log-ish spaced seconds: 100us .. 2min.  Wide enough for h2d dispatch
# at the bottom and a whole chaos-suite drain at the top.
DEFAULT_TIME_BUCKETS = (
    1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 120.0)

# The exposition-format metric-name grammar (Prometheus text format).
_PROM_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*\Z")


def prom_name(name: str) -> str:
    """Registry name -> Prometheus exposition name (``.``/``-`` map to
    ``_``).  The mangling is lossy, so :class:`MetricsRegistry` rejects
    two distinct registry names that would collide on the wire at
    registration time (e.g. ``serving.queue_depth`` vs
    ``serving_queue_depth`` — one would silently alias the other on
    every scrape)."""
    return name.replace(".", "_").replace("-", "_")


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Instrument:
    """Base: one named metric, one child state per label set."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "", registry=None):
        self.name = name
        self.help = help
        self._children: dict[tuple, object] = {}
        self._lock = registry._lock if registry is not None \
            else TracedLock("obs.metrics")

    def _child(self, labels: dict):
        key = _label_key(labels)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.setdefault(key, self._new_child())
        return child

    def _new_child(self):  # pragma: no cover - subclasses override
        raise NotImplementedError

    def series(self) -> list[tuple[tuple, object]]:
        """[(label key, child state)] sorted by label key."""
        with self._lock:
            return sorted(self._children.items())


class Counter(_Instrument):
    """Monotonically increasing float per label set."""

    kind = "counter"

    def _new_child(self):
        return [0.0]

    def inc(self, n: float = 1.0, **labels) -> None:
        if n < 0:
            raise ValueError(f"counters only go up; got inc({n})")
        child = self._child(labels)
        with self._lock:
            child[0] += n

    def value(self, **labels) -> float:
        return self._children.get(_label_key(labels), [0.0])[0]


class Gauge(_Instrument):
    """Last-write-wins float per label set (plus inc/dec for levels)."""

    kind = "gauge"

    def _new_child(self):
        return [0.0]

    def set(self, value: float, **labels) -> None:
        child = self._child(labels)
        with self._lock:  # mixed set/inc from two threads must not
            child[0] = float(value)  # lose either update

    def inc(self, n: float = 1.0, **labels) -> None:
        child = self._child(labels)
        with self._lock:
            child[0] += n

    def dec(self, n: float = 1.0, **labels) -> None:
        self.inc(-n, **labels)

    def value(self, **labels) -> float:
        return self._children.get(_label_key(labels), [0.0])[0]


class _HistState:
    __slots__ = ("counts", "total", "count", "vmin", "vmax")

    def __init__(self, n_edges: int):
        self.counts = [0] * (n_edges + 1)  # +inf bucket last
        self.total = 0.0
        self.count = 0
        self.vmin = None
        self.vmax = None


class Histogram(_Instrument):
    """Fixed-bucket-edge distribution per label set.

    ``buckets`` are the inclusive upper edges (ascending); one extra
    implicit +inf bucket catches the tail.  ``observe`` is a bisect +
    two adds — hot-loop safe.  Percentiles are *estimated* offline by
    linear interpolation inside the winning bucket
    (:func:`percentile_from_buckets`), exact min/max are tracked
    alongside so the estimate is clamped to observed range.
    """

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: tuple = DEFAULT_TIME_BUCKETS, registry=None):
        if not buckets or list(buckets) != sorted(buckets):
            raise ValueError(
                f"histogram buckets must be ascending and non-empty, "
                f"got {buckets}")
        super().__init__(name, help, registry=registry)
        self.buckets = tuple(float(b) for b in buckets)

    def _new_child(self):
        return _HistState(len(self.buckets))

    def observe(self, value: float, **labels) -> None:
        st = self._child(labels)
        with self._lock:
            st.counts[bisect.bisect_left(self.buckets, value)] += 1
            st.total += value
            st.count += 1
            if st.vmin is None or value < st.vmin:
                st.vmin = value
            if st.vmax is None or value > st.vmax:
                st.vmax = value


def percentile_from_buckets(snapshot: dict, q: float) -> float | None:
    """Estimate the ``q``-quantile (0..1) of one histogram-series
    snapshot (the dict :meth:`MetricsRegistry.snapshot` emits): find
    the bucket where the cumulative count crosses ``q * count`` and
    interpolate linearly inside it, clamped to the observed min/max.
    None when the series is empty."""
    count = snapshot.get("count", 0)
    if not count:
        return None
    edges = list(snapshot["buckets"])
    counts = list(snapshot["counts"])
    target = q * count
    lo_edge = snapshot.get("min") or 0.0
    cum = 0
    for i, c in enumerate(counts):
        nxt = cum + c
        if nxt >= target and c:
            lo = edges[i - 1] if i else min(lo_edge, edges[0])
            hi = edges[i] if i < len(edges) else (snapshot.get("max")
                                                  or edges[-1])
            frac = (target - cum) / c
            est = lo + (hi - lo) * frac
            if snapshot.get("min") is not None:
                est = max(est, snapshot["min"])
            if snapshot.get("max") is not None:
                est = min(est, snapshot["max"])
            return est
        cum = nxt
    return snapshot.get("max")


class MetricsRegistry:
    """One namespace of named instruments.

    ``counter`` / ``gauge`` / ``histogram`` get-or-create (re-asking
    for a name returns the same instrument; re-asking with a different
    kind raises — one name, one type).  ``snapshot()`` exports plain
    data; ``render_text()`` exports the Prometheus text format.
    """

    def __init__(self):
        # Leaf lock: every subsystem records INTO the registry while
        # holding its own lock; nothing is acquired under this one.
        self._lock = TracedLock("obs.registry")
        self._metrics: dict[str, _Instrument] = {}
        # prom_name -> registry name: the exposition mangling is lossy,
        # so a wire-name collision is detected HERE, at registration,
        # instead of silently interleaving two series on every scrape.
        self._prom_names: dict[str, str] = {}

    def _get(self, cls, name: str, help: str, **kw) -> _Instrument:
        m = self._metrics.get(name)
        if m is None:
            with self._lock:
                m = self._metrics.get(name)
                if m is None:
                    pname = prom_name(name)
                    if not _PROM_NAME_RE.match(pname):
                        raise ValueError(
                            f"metric name {name!r} does not map to a "
                            f"legal Prometheus name ({pname!r}); use "
                            "[a-zA-Z0-9_.:-] only")
                    other = self._prom_names.get(pname)
                    if other is not None and other != name:
                        raise ValueError(
                            f"metric {name!r} collides with {other!r} "
                            f"on the exposition name {pname!r} (the "
                            "./- -> _ mangling is lossy); rename one")
                    m = cls(name, help, registry=self, **kw)
                    self._prom_names[pname] = name
                    self._metrics[name] = m
        if not isinstance(m, cls):
            raise ValueError(
                f"metric {name!r} already registered as {m.kind}, "
                f"requested {cls.kind}")
        return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: tuple = DEFAULT_TIME_BUCKETS) -> Histogram:
        h = self._get(Histogram, name, help, buckets=buckets)
        if tuple(float(b) for b in buckets) != h.buckets:
            raise ValueError(
                f"histogram {name!r} already registered with edges "
                f"{h.buckets}; re-requested with {tuple(buckets)}")
        return h

    # ------------------------------------------------------- exporters

    def snapshot(self) -> dict:
        """``{name: {"kind", "help", "series": [{"labels", ...}]}}``,
        fully decoupled from live state (safe to mutate/serialize)."""
        out = {}
        with self._lock:
            metrics = list(self._metrics.items())
        for name, m in metrics:
            series = []
            for key, st in m.series():
                entry: dict = {"labels": dict(key)}
                if m.kind in ("counter", "gauge"):
                    entry["value"] = st[0]
                else:
                    entry.update(count=st.count, sum=st.total,
                                 min=st.vmin, max=st.vmax,
                                 buckets=list(m.buckets),
                                 counts=list(st.counts))
                series.append(entry)
            out[name] = {"kind": m.kind, "help": m.help, "series": series}
        return out

    def render_text(self) -> str:
        """Prometheus text exposition format (scrape-compatible:
        ``# HELP`` / ``# TYPE`` headers, ``_bucket``/``_sum``/
        ``_count`` histogram expansion, cumulative ``le`` buckets,
        escaped label values)."""
        def esc(v: str) -> str:
            return (v.replace("\\", "\\\\").replace('"', '\\"')
                     .replace("\n", "\\n"))

        def esc_help(v: str) -> str:
            # HELP text escapes backslash and line feed ONLY (the
            # text-format spec); a raw newline here would tear the
            # exposition stream mid-metric.
            return v.replace("\\", "\\\\").replace("\n", "\\n")

        lines = []
        for name, m in sorted(self.snapshot().items()):
            pname = prom_name(name)
            if m["help"]:
                lines.append(f"# HELP {pname} {esc_help(m['help'])}")
            lines.append(f"# TYPE {pname} {m['kind']}")
            for s in m["series"]:
                lab = ",".join(f'{k}="{esc(v)}"'
                               for k, v in sorted(s["labels"].items()))
                if m["kind"] in ("counter", "gauge"):
                    lines.append(f"{pname}{{{lab}}} {s['value']}"
                                 if lab else f"{pname} {s['value']}")
                else:
                    cum = 0
                    for edge, c in zip(s["buckets"] + [float("inf")],
                                       s["counts"]):
                        cum += c
                        le = ("+Inf" if edge == float("inf")
                              else repr(edge))
                        extra = f'{lab},le="{le}"' if lab \
                            else f'le="{le}"'
                        lines.append(f"{pname}_bucket{{{extra}}} {cum}")
                    suffix = f"{{{lab}}}" if lab else ""
                    lines.append(f"{pname}_sum{suffix} {s['sum']}")
                    lines.append(f"{pname}_count{suffix} {s['count']}")
        return "\n".join(lines) + ("\n" if lines else "")

    def compact(self) -> dict:
        """Small JSON-able view for attaching to bench/CI artifacts:
        counters/gauges as ``{name{labels}: value}``, histograms as
        ``{count, mean, min, max, p50, p95, p99}`` — min/max are the
        EXACT observed extremes the snapshot already tracks, so bench
        rows and SLO summaries see true worst-case latency, not just
        the bucket-interpolated p99."""
        out = {}
        for name, m in sorted(self.snapshot().items()):
            for s in m["series"]:
                lab = ",".join(f"{k}={v}"
                               for k, v in sorted(s["labels"].items()))
                key = f"{name}{{{lab}}}" if lab else name
                if m["kind"] in ("counter", "gauge"):
                    out[key] = s["value"]
                elif s["count"]:
                    out[key] = {
                        "count": s["count"],
                        "mean": s["sum"] / s["count"],
                        "min": s["min"], "max": s["max"],
                        "p50": percentile_from_buckets(s, 0.50),
                        "p95": percentile_from_buckets(s, 0.95),
                        "p99": percentile_from_buckets(s, 0.99),
                    }
        return out


def windowed_percentiles(new: dict, old: dict | None,
                         qs=(0.5, 0.95, 0.99)) -> dict | None:
    """Percentiles of the observations that landed BETWEEN two
    histogram-series snapshots (the dicts :meth:`MetricsRegistry.
    snapshot` emits): subtract the cumulative bucket counts and
    interpolate on the difference.  ``old=None`` means "since the
    beginning".  Returns ``{"count", qs...}`` or None when the window
    saw nothing.  The exact min/max are cumulative, not windowed, so
    the estimate is deliberately NOT clamped to them."""
    counts = list(new["counts"])
    count = new.get("count", 0)
    if old is not None:
        counts = [a - b for a, b in zip(counts, old["counts"])]
        count -= old.get("count", 0)
    if count <= 0:
        return None
    diff = {"count": count, "counts": counts,
            "buckets": list(new["buckets"]), "min": None, "max": None}
    out = {"count": count}
    for q in qs:
        out[f"p{int(round(q * 100))}"] = percentile_from_buckets(diff, q)
    return out


__all__ = ["MetricsRegistry", "Counter", "Gauge", "Histogram",
           "DEFAULT_TIME_BUCKETS", "percentile_from_buckets",
           "windowed_percentiles", "prom_name"]
