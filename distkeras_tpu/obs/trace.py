"""Structured event trace: nestable spans + point events as JSONL.

One run = one append-only JSONL file.  Every record carries a
monotonic timestamp ``t`` (``time.perf_counter`` — durations and
ordering are exact within the process), the run id, and the host
(``jax`` process index when available) / OS pid, so a multi-host run's
per-host files can be merged and a whole training or serving session
reconstructed — and *diffed* — offline (scripts/obs_report.py).

Record kinds:

``meta``   — first line: run id, host/pid, unix wall time anchor (maps
             monotonic ``t`` to wall clock), platform.
``event``  — a point in time: ``{"kind": "event", "name", "t",
             "fields": {...}}`` (chaos faults, supervisor attempts,
             admission rejects).
``span``   — a closed interval, written at END: ``{"kind": "span",
             "name", "t0", "dur", "id", "parent", "depth",
             "fields"}``.  Nesting is tracked per thread; ``parent``
             is the enclosing span's id (None at top level), so the
             tree reconstructs without begin/end pairing.
``metrics``— a full registry snapshot (the obs session appends one on
             close), so a trace file is self-contained for reports.

Thread safety: one lock around the file write; span stacks are
thread-local.  Writes are ``json.dumps`` + one ``write`` per record —
cheap enough for per-round/per-request cadence (the hot *inner* loops
record through the metrics registry, not the trace).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
import uuid

from distkeras_tpu.utils.locks import TracedLock


def _host_index() -> int:
    """jax process index if jax is already initialized; 0 otherwise.
    Deliberately does NOT import/initialize a backend."""
    import sys

    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return jax.process_index()
        except Exception:
            return 0
    return 0


class Span:
    """Handle yielded by :meth:`EventTrace.span` — carries the ids and
    accepts late fields (``span.fields["x"] = ...`` before exit)."""

    __slots__ = ("name", "id", "parent", "depth", "t0", "fields")

    def __init__(self, name, id, parent, depth, t0, fields):
        self.name = name
        self.id = id
        self.parent = parent
        self.depth = depth
        self.t0 = t0
        self.fields = fields


class EventTrace:
    """JSONL trace writer (see module docstring for the record model).

    ``path``: output file (parent dirs created).  ``run_id`` defaults
    to a fresh ``uuid4`` hex prefix.  Close (or use as a context
    manager) to flush; the file is line-buffered in between so a
    crashed run still leaves a parseable prefix.
    """

    def __init__(self, path: str, run_id: str | None = None):
        self.path = os.path.abspath(path)
        self.run_id = run_id or uuid.uuid4().hex[:12]
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        # "w", not "a": one run = one file (the module contract).
        # Reusing a path across runs must not blend two runs' records
        # — their monotonic clocks have different epochs, so a merged
        # file would report meaningless relative times.
        self._f = open(self.path, "w", buffering=1, encoding="utf-8")
        # Leaf lock: guards the file handle only (one write per
        # record); span stacks are thread-local, not locked.
        self._lock = TracedLock("obs.trace")
        self._tls = threading.local()
        self._next_id = 0
        self.host = _host_index()
        self.pid = os.getpid()
        self._write({"kind": "meta", "run": self.run_id,
                     "host": self.host, "pid": self.pid,
                     "t": time.perf_counter(),
                     "time_unix": time.time()})

    # ------------------------------------------------------------ write

    def _write(self, rec: dict) -> None:
        line = json.dumps(rec, default=str)
        with self._lock:
            if self._f.closed:
                return
            self._f.write(line + "\n")

    def _alloc_id(self) -> int:
        with self._lock:
            self._next_id += 1
            return self._next_id

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    # -------------------------------------------------------------- API

    def event(self, name: str, **fields) -> None:
        """Record a point event now."""
        st = self._stack()
        self._write({"kind": "event", "name": name,
                     "t": time.perf_counter(),
                     "span": st[-1].id if st else None,
                     "fields": fields})

    @contextlib.contextmanager
    def span(self, name: str, **fields):
        """Record a closed interval around the block; nests per
        thread.  The record is written at exit (one line per span)."""
        st = self._stack()
        parent = st[-1].id if st else None
        sp = Span(name=name, id=self._alloc_id(), parent=parent,
                  depth=len(st), t0=time.perf_counter(), fields=fields)
        st.append(sp)
        try:
            yield sp
        finally:
            st.pop()
            self._write({"kind": "span", "name": name, "t0": sp.t0,
                         "dur": time.perf_counter() - sp.t0,
                         "id": sp.id, "parent": sp.parent,
                         "depth": sp.depth, "fields": sp.fields})

    def metrics(self, snapshot: dict) -> None:
        """Append a full metrics-registry snapshot record."""
        self._write({"kind": "metrics", "t": time.perf_counter(),
                     "data": snapshot})

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def tail_trace(path: str, n: int, kinds=None) -> list[dict]:
    """The last ``n`` records of a trace file, with the same
    torn-final-line tolerance as :func:`read_trace` — safe against a
    LIVE writer (the telemetry server's ``/trace/tail`` calls this
    while the session is still appending; a half-flushed last line is
    dropped, never an error).  Reads a bounded window from the end of
    the file, not the whole trace.  ``kinds``: keep only these record
    kinds (e.g. ``("event",)``)."""
    if n <= 0:
        return []
    # Generous per-record bound: read enough tail bytes for n records
    # plus one potentially-torn leading line, growing if the window
    # started mid-file and yielded too few parseable lines.
    window = max(n * 512, 8192)
    records: list[dict] = []
    with open(path, "rb") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        while True:
            start = max(size - window, 0)
            f.seek(start)
            chunk = f.read(size - start).decode("utf-8", "replace")
            lines = chunk.splitlines()
            if start > 0 and lines:
                lines = lines[1:]  # first line may start mid-record
            records = []
            for line in lines:
                if not line.strip():
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn live write (tail) — lenient here
                if kinds is None or rec.get("kind") in kinds:
                    records.append(rec)
            if len(records) >= n or start == 0:
                break
            window *= 4
    return records[-n:]


def read_trace(path: str) -> list[dict]:
    """Parse one JSONL trace file back into records (strict: a
    truncated final line — crashed writer — is tolerated, anything
    else malformed raises)."""
    records = []
    with open(path, encoding="utf-8") as f:
        lines = f.read().splitlines()
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            records.append(json.loads(line))
        except ValueError:
            if i == len(lines) - 1:
                break  # torn final write from a crashed run
            raise
    return records


__all__ = ["EventTrace", "Span", "read_trace", "tail_trace"]
