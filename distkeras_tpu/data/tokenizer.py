"""Byte-level BPE tokenizer: the text -> tokens edge of the LM pipeline.

The reference has no tokenizer — its examples consume pre-vectorized
Spark DataFrame columns (reference: workflow.ipynb feature assembly);
its only text-adjacent path is the IMDB example's pre-tokenized ids.
The rebuild's flagship is a causal LM (models/transformer.py), so the
framework owes this edge: :class:`BPETokenizer` trains byte-level BPE
merges on a corpus, encodes text to int32 token arrays (the
``LMTrainer`` dataset contract), and decodes samples from
``generate()`` back to text.

The hot paths (train / encode) run in C++ (native/tokenizer.cc, via
ctypes) when a compiler is available, with an exact-equivalent pure
Python fallback — both implement greedy rank-order BPE, which is
deterministic, so the two paths produce identical ids (tested).

Byte-level means no out-of-vocabulary text exists: any bytes encode,
and decode is a lossless inverse.  Token ids: 0..255 are raw bytes,
256+i is merge i.
"""

from __future__ import annotations

import json

import numpy as np


def _merge(toks: list[int], pair: tuple[int, int], new_id: int) -> list[int]:
    """Replace every non-overlapping occurrence of ``pair`` (left to
    right) with ``new_id`` — the BPE rewrite shared by python-path
    training and encoding."""
    out, i = [], 0
    while i < len(toks):
        if i + 1 < len(toks) and (toks[i], toks[i + 1]) == pair:
            out.append(new_id)
            i += 2
        else:
            out.append(toks[i])
            i += 1
    return out


class BPETokenizer:
    """Byte-level BPE with a learned merge table.

    >>> tok = BPETokenizer.train(corpus_text, vocab_size=1024)
    >>> ids = tok.encode("hello world")     # np.int32 [n]
    >>> tok.decode(ids) == "hello world"    # lossless
    """

    def __init__(self, merges: np.ndarray):
        merges = np.ascontiguousarray(merges, dtype=np.int32)
        if merges.ndim != 2 or (len(merges) and merges.shape[1] != 2):
            raise ValueError(f"merges must be [n, 2] int32, got {merges.shape}")
        for i, (l, r) in enumerate(merges):
            if not (0 <= l < 256 + i and 0 <= r < 256 + i):
                raise ValueError(
                    f"merge {i} references token ids ({l}, {r}) that do not "
                    f"exist yet (valid: 0..{256 + i - 1}) — corrupt table?")
        self.merges = merges
        self._rank = {(int(l), int(r)): i for i, (l, r) in enumerate(merges)}

    # ------------------------------------------------------------ training

    @classmethod
    def train(cls, corpus: str | bytes, vocab_size: int = 512
              ) -> "BPETokenizer":
        """Learn ``vocab_size - 256`` merges from ``corpus``.

        Stops early (smaller vocab) when no adjacent pair repeats.
        """
        if vocab_size < 256:
            raise ValueError(
                f"vocab_size must be >= 256 (the byte alphabet), "
                f"got {vocab_size}")
        data = corpus.encode("utf-8") if isinstance(corpus, str) else corpus
        n_merges = vocab_size - 256
        if n_merges == 0 or len(data) < 2:
            return cls(np.empty((0, 2), np.int32))

        from distkeras_tpu.native import bpe_lib

        handle = bpe_lib()
        if handle is not None:
            buf = np.empty((n_merges, 2), np.int32)
            src = np.frombuffer(data, np.uint8)
            learned = handle.dkt_bpe_train(
                src.ctypes.data, len(src), n_merges, buf.ctypes.data)
            return cls(buf[:learned].copy())
        return cls(cls._train_py(data, n_merges))

    @staticmethod
    def _train_py(data: bytes, n_merges: int) -> np.ndarray:
        toks = list(data)
        merges = []
        for m in range(n_merges):
            counts: dict[tuple[int, int], int] = {}
            for pair in zip(toks, toks[1:]):
                counts[pair] = counts.get(pair, 0) + 1
            if not counts:
                break
            # max count, ties to the smallest pair — matches the C++
            # (std::map iterates sorted; strict > keeps the first max).
            best = min(counts, key=lambda p: (-counts[p], p))
            if counts[best] < 2:
                break
            merges.append(best)
            toks = _merge(toks, best, 256 + m)
        return np.asarray(merges, np.int32).reshape(-1, 2)

    # ------------------------------------------------------------ coding

    @property
    def vocab_size(self) -> int:
        return 256 + len(self.merges)

    def encode(self, text: str | bytes) -> np.ndarray:
        """Encode to int32 token ids (never fails: byte-level)."""
        data = text.encode("utf-8") if isinstance(text, str) else text
        if not data:
            return np.empty((0,), np.int32)

        from distkeras_tpu.native import bpe_lib

        handle = bpe_lib()
        if handle is not None:
            src = np.frombuffer(data, np.uint8)
            out = np.empty(len(src), np.int32)
            n = handle.dkt_bpe_encode(
                self.merges.ctypes.data, len(self.merges),
                src.ctypes.data, len(src), out.ctypes.data)
            return out[:n].copy()
        return self._encode_py(data)

    def _encode_py(self, data: bytes) -> np.ndarray:
        toks = list(data)
        rank = self._rank
        while True:
            # Lowest-rank pair present anywhere; merging can only create
            # pairs of *higher* rank (a merge id only appears in later
            # rules), so rank order is globally safe.
            best = None
            for pair in set(zip(toks, toks[1:])):
                r = rank.get(pair)
                if r is not None and (best is None or r < best[0]):
                    best = (r, pair)
            if best is None:
                break
            r, pair = best
            toks = _merge(toks, pair, 256 + r)
        return np.asarray(toks, np.int32)

    def decode(self, ids, errors: str = "replace") -> str:
        """Decode token ids back to text (lossless for encode output)."""
        return bytes(self.decode_bytes(ids)).decode("utf-8", errors=errors)

    def decode_bytes(self, ids) -> np.ndarray:
        ids = np.ascontiguousarray(ids, dtype=np.int32)
        if ids.ndim != 1:
            raise ValueError(f"ids must be 1-D, got shape {ids.shape}")
        if ids.size == 0:
            return np.empty((0,), np.uint8)
        if ids.min() < 0 or ids.max() >= self.vocab_size:
            raise ValueError(
                f"token id out of range for vocab_size={self.vocab_size}")

        from distkeras_tpu.native import bpe_lib

        handle = bpe_lib()
        if handle is not None:
            # Exact output size from the per-id expansion lengths.
            cap = int(np.take(self._expansion_lens(), ids).sum())
            out = np.empty(cap, np.uint8)
            n = handle.dkt_bpe_decode(
                self.merges.ctypes.data, len(self.merges),
                ids.ctypes.data, len(ids), out.ctypes.data, cap)
            if n < 0:  # pragma: no cover - guarded by the range check
                raise ValueError("native BPE decode failed")
            return out[:n].copy()
        table = self._expansion_table()
        return np.asarray(
            [b for i in ids for b in table[int(i)]], np.uint8)

    def _expansion_table(self) -> list[bytes]:
        table: list[bytes] = [bytes([b]) for b in range(256)]
        for l, r in self.merges:
            table.append(table[int(l)] + table[int(r)])
        return table

    def _expansion_lens(self) -> np.ndarray:
        lens = [1] * 256
        for l, r in self.merges:
            lens.append(lens[int(l)] + lens[int(r)])
        return np.asarray(lens, np.int64)

    # ------------------------------------------------------------ persist

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump({"format": "dkt-bpe-v1",
                       "merges": self.merges.tolist()}, f)

    @classmethod
    def load(cls, path: str) -> "BPETokenizer":
        with open(path) as f:
            blob = json.load(f)
        if blob.get("format") != "dkt-bpe-v1":
            raise ValueError(f"not a dkt-bpe-v1 file: {path}")
        return cls(np.asarray(blob["merges"], np.int32).reshape(-1, 2))

    # ------------------------------------------------------------ batching

    def encode_corpus(self, text: str | bytes, seq_len: int) -> np.ndarray:
        """Encode and pack into LMTrainer rows ``[N, seq_len + 1]``.

        Consecutive windows with one-token overlap (each row carries
        inputs plus the shifted targets, the trainers/lm.py contract);
        the tail remainder is dropped.
        """
        ids = self.encode(text)
        n = (len(ids) - 1) // seq_len
        if n < 1:
            raise ValueError(
                f"corpus encodes to {len(ids)} tokens; one row needs "
                f"{seq_len + 1}")
        windows = np.lib.stride_tricks.sliding_window_view(ids, seq_len + 1)
        return np.ascontiguousarray(windows[::seq_len][:n])
