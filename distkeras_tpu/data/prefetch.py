"""Background batch prefetching.

The reference overlaps input with compute for free — Spark executors
iterate their partition while the JVM fetches the next (reference:
workers.py consuming mapPartitions iterators).  Here the equivalent is
a small host-side pipeline: a daemon thread runs the batch iterator
(shuffle-gather, windows, dtype conversion) ``depth`` elements ahead of
the training loop, so batch preparation overlaps the device step that
jax dispatches asynchronously.
"""

from __future__ import annotations

import collections
import queue
import threading
from typing import Iterable, Iterator

from distkeras_tpu import obs


class DeviceFeed:
    """Stream host batches to the device, ``depth`` items in flight.

    ``jax.device_put`` is asynchronous: issuing the next window's
    transfer *before* the consumer executes on the current one lets the
    host->device copy ride under the device step.  Transfers are issued
    from the consuming thread — on remote-attached devices (the axon
    relay) a second thread contends on the transport and makes things
    *slower*, so unlike :class:`Prefetcher` this is deliberately
    single-threaded lookahead, not a producer thread.

    Feed it window-stacked batches (``[steps_per_call, B, ...]`` pytrees
    of numpy arrays) and consume with a multi-step jitted call: one
    execution per window amortizes the per-dispatch overhead that
    dominates small-step training, and the next window's bytes stream
    while the scan runs.  Ship the smallest dtype you can (uint8 pixels,
    int32 tokens) and expand/normalize on device — the h2d link, not
    HBM, is the input pipeline's narrow point (see ModelAdapter's
    ``preprocess`` hook).
    """

    def __init__(self, source: Iterable, depth: int = 2, sharding=None):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self._source = source
        self._depth = depth
        self._sharding = sharding

    def __iter__(self):
        import jax

        pending: collections.deque = collections.deque()
        for item in self._source:
            # device_put maps over pytrees itself and coalesces the
            # leaves into one batched transfer.  The obs span measures
            # *dispatch* wall time (the transfer itself rides under
            # the device step — that overlap is the point); the bytes
            # counter sizes the h2d stream exactly.
            if obs.active() is not None:
                obs.count("data.h2d.bytes",
                          sum(getattr(x, "nbytes", 0)
                              for x in jax.tree.leaves(item)))
                obs.count("data.h2d.items")
            with obs.span("data.h2d"):
                pending.append(jax.device_put(item, self._sharding)
                               if self._sharding is not None
                               else jax.device_put(item))
            if len(pending) > self._depth:
                yield pending.popleft()
        while pending:
            yield pending.popleft()


class Prefetcher:
    """Iterate ``source`` on a background thread, ``depth`` items ahead.

    Exceptions in the source re-raise in the consumer (once; the
    iterator is exhausted afterwards, like a generator).  Abandoning the
    iterator mid-stream is safe: ``close()`` — called by ``__del__`` and
    usable explicitly — unblocks and stops the producer thread.
    """

    _DONE = object()

    def __init__(self, source: Iterable, depth: int = 2):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._err: BaseException | None = None
        self._finished = False
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, args=(iter(source),),
            name="dkt-prefetch", daemon=True)
        self._thread.start()

    def _put(self, item) -> bool:
        """Enqueue unless closed; False means stop producing."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _run(self, it: Iterator) -> None:
        try:
            for item in it:
                if not self._put(item):
                    return
        except BaseException as e:  # propagate to consumer
            self._err = e
        finally:
            self._put(self._DONE)

    def close(self) -> None:
        """Stop the producer and release buffered items.

        Also wakes any consumer already blocked in ``__next__`` (the
        drain below could otherwise swallow the producer's ``_DONE``
        sentinel and leave that consumer blocked forever).
        """
        self._stop.set()
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._finished = True
        try:
            self._q.put_nowait(self._DONE)
        except queue.Full:
            pass  # a queued item will wake the consumer instead

    def __del__(self):  # pragma: no cover - GC timing
        self.close()

    def __iter__(self):
        return self

    def __next__(self):
        if self._finished:
            raise StopIteration
        while True:
            try:
                item = self._q.get(timeout=0.05)
                break
            except queue.Empty:
                if self._stop.is_set():
                    raise StopIteration from None
        # Buffer occupancy at consumption: a gauge pinned near 0 means
        # the producer can't keep up (input-bound run); near `depth`
        # means compute-bound.  qsize() takes the queue mutex, so it
        # is guarded — the disabled path must stay free.
        if obs.active() is not None:
            obs.gauge("data.prefetch.occupancy", self._q.qsize())
        if item is self._DONE:
            self._finished = True
            if self._err is not None:
                err, self._err = self._err, None
                raise err
            raise StopIteration
        return item
