"""Column-oriented in-memory Dataset (L3' — replaces Spark DataFrames).

The reference's data plane is a Spark DataFrame: named columns, lazy
transforms, partitions iterated inside executors to feed
``model.train_on_batch`` (reference: distkeras/workers.py; SURVEY.md
§3.5 shows the column-to-column pipeline).  The TPU-native replacement
keeps the *column* model — transformers append/modify named columns,
predictors append a prediction column — but stores columns as host
numpy arrays and feeds devices through sharded, double-buffered batch
iteration instead of RDD partition iterators.

Multi-host: ``shard(host_id, num_hosts)`` gives each host process its
slice, the moral equivalent of Spark's partition placement; on-device
the batch is then split across local devices by the trainer's
``NamedSharding``.
"""

from __future__ import annotations

from typing import Iterator, Mapping

import numpy as np


class Dataset:
    """Immutable dict of equal-length named numpy columns."""

    def __init__(self, columns: Mapping[str, np.ndarray]):
        if not columns:
            raise ValueError("Dataset needs at least one column")
        lengths = {k: len(v) for k, v in columns.items()}
        if len(set(lengths.values())) != 1:
            raise ValueError(f"Column length mismatch: {lengths}")
        self._cols = {k: np.asarray(v) for k, v in columns.items()}

    # ------------------------------------------------------------ basics

    def __len__(self) -> int:
        return len(next(iter(self._cols.values())))

    @property
    def columns(self) -> tuple[str, ...]:
        return tuple(self._cols)

    def __getitem__(self, name: str) -> np.ndarray:
        return self._cols[name]

    def with_column(self, name: str, values: np.ndarray) -> "Dataset":
        cols = dict(self._cols)
        cols[name] = np.asarray(values)
        return Dataset(cols)

    def drop(self, *names: str) -> "Dataset":
        return Dataset({k: v for k, v in self._cols.items() if k not in names})

    def select(self, *names: str) -> "Dataset":
        return Dataset({k: self._cols[k] for k in names})

    def take(self, n: int) -> "Dataset":
        return Dataset({k: v[:n] for k, v in self._cols.items()})

    # ------------------------------------------------------- constructors

    @staticmethod
    def from_arrays(features: np.ndarray, labels: np.ndarray | None = None,
                    features_col: str = "features", label_col: str = "label"
                    ) -> "Dataset":
        cols = {features_col: features}
        if labels is not None:
            cols[label_col] = labels
        return Dataset(cols)

    @staticmethod
    def from_csv(path: str, label_col: str | int | None = None,
                 features_col: str = "features", dtype=np.float32,
                 delimiter: str = ",", skip_header: int = 1) -> "Dataset":
        """Read a numeric CSV into one features matrix (+ optional label).

        Covers the reference's canonical tabular flow (workflow.ipynb
        reads the ATLAS Higgs CSV then assembles a feature vector).
        """
        if not skip_header:
            # Headerless numeric CSV: label_col may be an integer index.
            # ndmin=2 keeps one-column files as [n, 1], not a transposed
            # [1, n] (np.atleast_2d on a 1-D read would do the latter).
            data = np.loadtxt(path, delimiter=delimiter, dtype=dtype, ndmin=2)
            if label_col is None:
                return Dataset({features_col: data})
            if not isinstance(label_col, int):
                raise ValueError(
                    "headerless CSV (skip_header=0): label_col must be a "
                    f"column index, got {label_col!r}")
            labels = data[:, label_col]
            feats = np.delete(data, label_col, axis=1)
            return Dataset({features_col: feats, "label": labels})
        # skip_header semantics: the number of header lines; column names
        # are read from the *last* of them (genfromtxt's skip_header counts
        # lines skipped before the names line).
        # dtype=None infers per-column dtypes, so a non-numeric column
        # (string ids etc.) raises at the astype below instead of turning
        # into silent NaNs.
        raw = np.genfromtxt(
            path, delimiter=delimiter, names=True, dtype=None,
            skip_header=max(0, skip_header - 1), encoding="utf-8")
        names = list(raw.dtype.names)
        if label_col is not None and label_col not in names:
            raise ValueError(f"label column {label_col!r} not in {names}")
        feat_names = [n for n in names if n != label_col]
        feats = np.stack([raw[n].astype(dtype) for n in feat_names], axis=1)
        cols = {features_col: feats}
        if label_col is not None:
            cols[label_col] = raw[label_col]
        return Dataset(cols)

    # --------------------------------------------------------- reshaping

    def shuffle(self, seed: int | None = None) -> "Dataset":
        """Global random permutation (reference: distkeras/utils.py::shuffle,
        which sorted a Spark DataFrame by a random key).

        The row gather runs through the native threaded loader when
        built (distkeras_tpu.native), numpy fancy indexing otherwise.
        """
        from distkeras_tpu.native import gather_rows

        rng = np.random.default_rng(seed)
        perm = rng.permutation(len(self))
        return Dataset({k: gather_rows(v, perm)
                        for k, v in self._cols.items()})

    def split(self, frac: float, seed: int | None = None
              ) -> tuple["Dataset", "Dataset"]:
        """Random (train, held-out) split; ``frac`` is the first part.

        The reference delegates splitting to Spark's
        ``randomSplit`` (workflow.ipynb); here it is a permutation
        slice, deterministic under ``seed``.
        """
        if not 0.0 < frac < 1.0:
            raise ValueError(f"frac must be in (0, 1), got {frac}")
        n = len(self)
        cut = round(n * frac)  # int() truncation would undershoot e.g.
        if cut == 0 or cut == n:  # 100 * 0.29 == 28.999…
            raise ValueError(
                f"split frac={frac} of {n} rows leaves an empty part")
        from distkeras_tpu.native import gather_rows

        perm = np.random.default_rng(seed).permutation(n)
        first, second = perm[:cut], perm[cut:]
        return (
            Dataset({k: gather_rows(v, first)
                     for k, v in self._cols.items()}),
            Dataset({k: gather_rows(v, second)
                     for k, v in self._cols.items()}))

    def shard(self, index: int, num_shards: int) -> "Dataset":
        """Strided host shard — each host keeps rows i, i+num_shards, ...

        The multi-host analogue of Spark assigning partitions to
        executors; strided (not contiguous) so class distribution stays
        balanced without a shuffle.
        """
        if not (0 <= index < num_shards):
            raise ValueError(f"shard index {index} out of range {num_shards}")
        return Dataset({k: v[index::num_shards] for k, v in self._cols.items()})

    def repeat(self, epochs: int) -> "Dataset":
        return Dataset({k: np.concatenate([v] * epochs)
                        for k, v in self._cols.items()})

    # --------------------------------------------------------- iteration

    def batches(self, batch_size: int, *, features_col: str = "features",
                label_col: str | None = "label", drop_remainder: bool = True,
                window: int | None = None, prefetch: int = 0
                ) -> Iterator[tuple[np.ndarray, np.ndarray] | np.ndarray]:
        """Yield (x, y) minibatches; with ``window``, yield [w, B, ...] stacks.

        ``window`` serves the accumulation trainers (ADAG/DynSGD): one
        yielded element carries ``window`` microbatches so a single
        jitted scan step consumes them (SURVEY.md §7.4).
        ``drop_remainder=True`` keeps shapes static for XLA.
        ``prefetch=N`` stages batch preparation N elements ahead on a
        background thread (data.prefetch.Prefetcher).
        """
        if window and not drop_remainder:
            raise ValueError(
                "window requires drop_remainder=True: a partial tail "
                "cannot be reshaped to [window, batch, ...]")

        def gen():
            n = len(self)
            x = self._cols[features_col]
            y = self._cols[label_col] if label_col else None
            step = batch_size * (window or 1)
            end = n - (n % step) if drop_remainder else n
            for i in range(0, end, step):
                xb = x[i:i + step]
                yb = y[i:i + step] if y is not None else None
                if window:
                    xb = xb.reshape((window, batch_size) + xb.shape[1:])
                    if yb is not None:
                        yb = yb.reshape((window, batch_size) + yb.shape[1:])
                yield (xb, yb) if y is not None else xb

        if prefetch:
            from distkeras_tpu.data.prefetch import Prefetcher

            return Prefetcher(gen(), depth=prefetch)
        return gen()

    def num_batches(self, batch_size: int, window: int | None = None) -> int:
        return len(self) // (batch_size * (window or 1))
