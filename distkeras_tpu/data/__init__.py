from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.data.tokenizer import BPETokenizer
from distkeras_tpu.data.transformers import (
    Transformer,
    OneHotTransformer,
    LabelIndexTransformer,
    MinMaxTransformer,
    ReshapeTransformer,
    DenseTransformer,
)

__all__ = [
    "Dataset",
    "BPETokenizer",
    "Transformer",
    "OneHotTransformer",
    "LabelIndexTransformer",
    "MinMaxTransformer",
    "ReshapeTransformer",
    "DenseTransformer",
]
