from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.data.transformers import (
    Transformer,
    OneHotTransformer,
    LabelIndexTransformer,
    MinMaxTransformer,
    ReshapeTransformer,
    DenseTransformer,
)

__all__ = [
    "Dataset",
    "Transformer",
    "OneHotTransformer",
    "LabelIndexTransformer",
    "MinMaxTransformer",
    "ReshapeTransformer",
    "DenseTransformer",
]
