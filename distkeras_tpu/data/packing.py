"""Document packing for LM training rows.

Real corpora are many variable-length documents; padding each to
``seq_len`` wastes MXU cycles on dead positions (the shorter the docs,
the worse — at 10% mean fill, 90% of the FLOPs train nothing).
Packing concatenates documents into full rows and carries a parallel
``segment_ids`` array so attention stays within-document
(ops/attention segment masking) and the loss skips cross-boundary and
padding targets (transformer.lm_loss / lm_nll ``segment_ids=``).

The reference has nothing comparable (its text example pads fixed-width
IMDB reviews, reference: examples); this is the standard t5x/maxtext
pretraining input treatment, rebuilt TPU-first: static [N, S+1] shapes,
mask-driven semantics, zero host-side re-layout at step time.
"""

from __future__ import annotations

import numpy as np


def pack_documents(docs, seq_len: int, pad_id: int = 0):
    """Pack token documents into LM rows.

    ``docs``: iterable of 1-D int token sequences (each one document).
    Returns ``(rows [N, seq_len+1] int32, segments [N, seq_len+1]
    int32)`` — the trainers/lm.py row contract (inputs + shifted
    targets) plus per-position document ids: 1, 2, ... within each row,
    0 for padding.  Feed both to ``lm_loss(..., segment_ids=segments)``
    (or ``LMTrainer.train(rows, segments=segments)``).

    Greedy streaming fill: documents are laid end-to-end; a document
    longer than the remaining row space CONTINUES into the next row
    under a fresh segment id (its continuation attends only its own
    row's slice — context resets at the row boundary, the standard
    packing trade).  Single-token tails are dropped (a segment needs
    >= 2 positions to yield one trainable target).  The final partial
    row is padded with ``pad_id`` / segment 0.
    """
    if seq_len < 1:
        raise ValueError(f"seq_len must be >= 1, got {seq_len}")
    width = seq_len + 1
    rows, segs = [], []
    cur_r = np.full((width,), pad_id, np.int64)
    cur_s = np.zeros((width,), np.int32)
    fill, next_seg = 0, 1

    def flush():
        nonlocal cur_r, cur_s, fill, next_seg
        if fill:
            rows.append(cur_r.copy())
            segs.append(cur_s.copy())
        cur_r = np.full((width,), pad_id, np.int64)
        cur_s = np.zeros((width,), np.int32)
        fill, next_seg = 0, 1

    for doc in docs:
        doc = np.asarray(doc).ravel()
        if doc.size < 2:
            continue  # no trainable target even alone
        start = 0
        while start < doc.size:
            if fill >= width - 1:
                flush()  # < 2 free slots: nothing trainable fits
            take = min(doc.size - start, width - fill)
            if doc.size - start - take == 1:
                take -= 1  # don't strand a 1-token (untrainable) tail
            if take < 2 and fill:
                # A 1-token chunk is untrainable waste (its target is
                # boundary-masked): start this document on a fresh row
                # instead.  Fresh rows always fit >= 2 (width >= 3;
                # the seq_len=1 edge accepts the degenerate chunk).
                flush()
                continue
            cur_r[fill:fill + take] = doc[start:start + take]
            cur_s[fill:fill + take] = next_seg
            fill += take
            next_seg += 1
            start += take
    flush()
    if not rows:
        raise ValueError(
            f"no document provided >= 2 tokens; nothing to pack into "
            f"rows of seq_len={seq_len}")
    return (np.stack(rows).astype(np.int32),
            np.stack(segs).astype(np.int32))


def packing_efficiency(segments) -> float:
    """Fraction of positions carrying real tokens (segment != 0)."""
    segments = np.asarray(segments)
    return float((segments != 0).mean())
