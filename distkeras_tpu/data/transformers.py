"""Column transformers (reference parity: distkeras/transformers.py).

Each transformer is ``transform(dataset) -> dataset`` appending or
replacing named columns, mirroring the reference's Spark-DataFrame
transformers one for one (SURVEY.md §2): OneHotTransformer,
LabelIndexTransformer, MinMaxTransformer, ReshapeTransformer,
DenseTransformer.  They are vectorized numpy ops on host columns — the
per-row Python udf of the reference becomes one array expression.
"""

from __future__ import annotations

import numpy as np

from distkeras_tpu.data.dataset import Dataset


class Transformer:
    """Base: subclasses implement ``transform(dataset) -> dataset``."""

    def transform(self, dataset: Dataset) -> Dataset:  # pragma: no cover
        raise NotImplementedError

    def __call__(self, dataset: Dataset) -> Dataset:
        return self.transform(dataset)


class OneHotTransformer(Transformer):
    """Integer label column -> one-hot float vector column.

    Reference parity: distkeras/transformers.py::OneHotTransformer.
    """

    def __init__(self, num_classes: int, input_col: str = "label",
                 output_col: str = "label_onehot"):
        self.num_classes = num_classes
        self.input_col = input_col
        self.output_col = output_col

    def transform(self, dataset: Dataset) -> Dataset:
        labels = dataset[self.input_col].astype(np.int64)
        onehot = np.eye(self.num_classes, dtype=np.float32)[labels]
        return dataset.with_column(self.output_col, onehot)


class LabelIndexTransformer(Transformer):
    """Prediction-vector column -> argmax index column.

    Reference parity: distkeras/transformers.py::LabelIndexTransformer
    (used after ModelPredictor to turn raw outputs into class labels,
    SURVEY.md §3.5).
    """

    def __init__(self, input_col: str = "prediction",
                 output_col: str = "prediction_index"):
        self.input_col = input_col
        self.output_col = output_col

    def transform(self, dataset: Dataset) -> Dataset:
        preds = dataset[self.input_col]
        return dataset.with_column(self.output_col,
                                   np.argmax(preds, axis=-1).astype(np.int64))


class MinMaxTransformer(Transformer):
    """Scale a column to [new_min, new_max] given observed/known bounds.

    Reference parity: distkeras/transformers.py::MinMaxTransformer.
    Bounds may be supplied (the reference requires them) or computed
    from the data when omitted.
    """

    def __init__(self, input_col: str = "features",
                 output_col: str | None = None,
                 o_min: float | None = None, o_max: float | None = None,
                 n_min: float = 0.0, n_max: float = 1.0):
        self.input_col = input_col
        self.output_col = output_col or input_col
        self.o_min, self.o_max = o_min, o_max
        self.n_min, self.n_max = n_min, n_max

    def transform(self, dataset: Dataset) -> Dataset:
        x = dataset[self.input_col].astype(np.float32)
        o_min = self.o_min if self.o_min is not None else float(x.min())
        o_max = self.o_max if self.o_max is not None else float(x.max())
        scale = (self.n_max - self.n_min) / max(o_max - o_min, 1e-12)
        return dataset.with_column(self.output_col,
                                   (x - o_min) * scale + self.n_min)


class StandardScaleTransformer(Transformer):
    """Per-feature standardization: (x - mean) / std.

    The reference's canonical workflow standardizes features with Spark
    ML's StandardScaler before any dist-keras trainer sees them
    (SURVEY.md §3.5 pipeline); this is that stage, Dataset-native.
    Fit-once semantics: statistics are computed from the *first* dataset
    transformed (or passed explicitly) and reused for every later call,
    so train and test get the same scaling.
    """

    def __init__(self, input_col: str = "features",
                 output_col: str | None = None,
                 mean: np.ndarray | None = None,
                 std: np.ndarray | None = None):
        self.input_col = input_col
        self.output_col = output_col or input_col
        self.mean, self.std = mean, std

    def transform(self, dataset: Dataset) -> Dataset:
        x = dataset[self.input_col].astype(np.float32)
        if self.mean is None:
            self.mean = x.mean(axis=0)
        if self.std is None:
            self.std = x.std(axis=0)
        return dataset.with_column(
            self.output_col, (x - self.mean) / np.maximum(self.std, 1e-12))


class ReshapeTransformer(Transformer):
    """Reshape each row of a column (flat vector -> image tensor).

    Reference parity: distkeras/transformers.py::ReshapeTransformer
    (used to feed CNNs from flat Spark vectors).
    """

    def __init__(self, input_col: str, output_col: str, shape: tuple):
        self.input_col = input_col
        self.output_col = output_col
        self.shape = tuple(shape)

    def transform(self, dataset: Dataset) -> Dataset:
        x = dataset[self.input_col]
        return dataset.with_column(self.output_col,
                                   x.reshape((len(x),) + self.shape))


class DenseTransformer(Transformer):
    """Sparse (indices, values) columns -> dense vector column.

    Reference parity: distkeras/transformers.py::DenseTransformer
    (Spark sparse vectors -> dense).  Input is a pair of object-arrays of
    per-row index/value arrays (scalars accepted as length-1 rows), or an
    already-dense column (passthrough).

    Behavior note vs the per-row-loop implementation: negative sparse
    indices raise ``ValueError`` here instead of silently wrapping to the
    end of the row — wrapping was never meaningful for Spark sparse
    vectors, whose indices are non-negative by contract.
    """

    def __init__(self, input_col: str = "features",
                 output_col: str | None = None, size: int | None = None,
                 indices_col: str | None = None, values_col: str | None = None):
        self.input_col = input_col
        self.output_col = output_col or input_col
        self.size = size
        self.indices_col = indices_col
        self.values_col = values_col

    def transform(self, dataset: Dataset) -> Dataset:
        if self.indices_col and self.values_col:
            idx = dataset[self.indices_col]
            val = dataset[self.values_col]
            if self.size is None:
                raise ValueError("DenseTransformer needs size= for sparse input")
            out = np.zeros((len(dataset), self.size), dtype=np.float32)
            if len(dataset):
                # One flattened scatter instead of a per-row Python loop:
                # ragged per-row index/value arrays concatenate to flat
                # (row, col, val) triples and assign in a single fancy
                # index (duplicate (row, col) keeps last-wins semantics,
                # same as the row-at-a-time assignment).  atleast_1d
                # accepts scalar rows (a single index/value per row).
                idx = [np.atleast_1d(ii) for ii in idx]
                val = [np.atleast_1d(vv) for vv in val]
                lengths = np.fromiter((len(ii) for ii in idx),
                                      dtype=np.int64, count=len(dataset))
                vlengths = np.fromiter((len(vv) for vv in val),
                                       dtype=np.int64, count=len(dataset))
                # Per-row, not aggregate: equal totals with unequal rows
                # would silently shift values across rows.
                if not np.array_equal(lengths, vlengths):
                    bad = int(np.nonzero(lengths != vlengths)[0][0])
                    raise ValueError(
                        f"indices/values length mismatch at row {bad}: "
                        f"{lengths[bad]} indices vs {vlengths[bad]} values")
                if lengths.sum():
                    rows = np.repeat(np.arange(len(dataset)), lengths)
                    cols = np.concatenate(
                        [np.asarray(ii, np.int64) for ii in idx])
                    vals = np.concatenate(
                        [np.asarray(vv, np.float32) for vv in val])
                    if cols.size and (cols.min() < 0
                                      or cols.max() >= self.size):
                        raise ValueError(
                            f"sparse index out of range for size="
                            f"{self.size}: [{cols.min()}, {cols.max()}]")
                    out[rows, cols] = vals
            return dataset.with_column(self.output_col, out)
        # Already dense: ensure float32 ndarray.
        x = np.asarray(dataset[self.input_col], dtype=np.float32)
        return dataset.with_column(self.output_col, x)
