from distkeras_tpu.utils.serialization import (
    serialize_keras_model,
    deserialize_keras_model,
)
from distkeras_tpu.utils.misc import to_dense_vector, uniform_weights
from distkeras_tpu.utils.profiling import StepTimer, annotate, trace

__all__ = [
    "serialize_keras_model",
    "deserialize_keras_model",
    "to_dense_vector",
    "uniform_weights",
    "StepTimer",
    "annotate",
    "trace",
]
