"""Instrumented locks + the runtime lock-order sanitizer.

Every lock in the threaded subsystems (serving admission, the prefix
pool, the obs registry/trace/SLO planes, the native build cache) is
constructed through :func:`TracedLock` / :func:`TracedRLock` instead
of raw ``threading.Lock``/``RLock`` (enforced by the source lint's
``raw-lock`` rule).  The factories are free when the sanitizer is off
— they return the *raw* stdlib lock, not a wrapper, so production
pays literally nothing — and return instrumented locks when it is on
(``DKT_LOCK_SANITIZER=1`` in the environment, or
:func:`enable_sanitizer`; tests/conftest.py turns it on for the whole
tier-1 suite).

What the sanitizer checks, in the spirit of ThreadSanitizer's
lock-order/deadlock detection applied at the Python-threading layer
this codebase actually runs on:

- **Lock-order cycles.**  Each thread's held-lock stack is tracked;
  acquiring B while holding A records the edge A -> B in one global
  lock-order graph (per lock *instance*, so unrelated locks sharing a
  name never alias).  An acquisition that would close a cycle —
  somewhere, some thread acquired these locks in the opposite order —
  is a potential deadlock even if the interleaving never actually
  wedged: it is reported as a :class:`LockOrderViolation` carrying
  BOTH acquisition stacks (the recorded first-observed edge and the
  current attempt).  Only unbounded blocking acquires participate:
  try-acquires and bounded waits cannot deadlock (the standard
  avoidance idiom), so they neither raise nor record edges, and
  edges commit only after a successful acquire — a failed attempt
  never poisons the graph.
- **Same-thread double-acquire of a non-reentrant lock.**  A plain
  ``Lock`` re-acquired by its owner deadlocks *forever*; the sanitizer
  raises instead of blocking, so the regression test for the PR-8
  subscriber-under-lock deadlock asserts a report, not a timeout.
- **Callbacks fired under a lock.**  Subscriber/callback fire sites
  call :func:`assert_unlocked` first: if the calling thread still
  holds any sanitized lock, the callback could re-enter the subsystem
  and deadlock (the exact PR-8 ``slo.breach``-subscriber shape) — the
  guard reports it with the held locks' acquisition stacks.
- **Held-time / contention telemetry.**  When an obs session is
  active, every instrumented release records a ``lock.held_s{lock=}``
  histogram observation and every contended acquire a
  ``lock.wait_s{lock=}`` one — the live ``/metrics`` plane then
  exposes lock pressure per subsystem for free.

Violations are always *recorded* (:func:`violations`;
tests/conftest.py fails any test that produced one) and by default
also *raised* at the offending acquire/fire site
(``DKT_LOCK_SANITIZER=warn`` records only).  A certain-deadlock
double-acquire always raises — proceeding would hang the process.

Guaranteed jax-free (source lint ``jax-free`` ledger): this module
feeds the obs metrics registry and is imported by the live telemetry
plane's modules, which must never be able to trigger device work.
The obs hook goes through ``sys.modules`` — it never *imports*
anything, so the module stays loadable under obs_report.py's
no-framework stub loader.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import sys
import threading
import time

_STACK_LIMIT = 14

# Global, monotone lock ids: survive enable/disable cycles so a stale
# lock from a previous sanitizer window can never alias a fresh one
# (id() reuse would fabricate phantom graph edges).
_UIDS = itertools.count(1)


class LockOrderViolation(RuntimeError):
    """A thread-safety discipline violation the sanitizer detected.

    ``kind`` is one of ``"cycle"`` (lock-order inversion — potential
    deadlock), ``"double-acquire"`` (same thread re-acquiring a
    non-reentrant lock — certain deadlock), or ``"held-in-callback"``
    (a registered callback fired while the calling thread holds a
    sanitized lock)."""

    def __init__(self, kind: str, message: str):
        super().__init__(message)
        self.kind = kind


@dataclasses.dataclass(frozen=True)
class Violation:
    """One recorded finding: the kind, a one-line message, and the
    acquisition stacks involved — ``stacks`` is a tuple of
    ``(label, (frame_line, ...))`` pairs."""

    kind: str
    message: str
    thread: str
    stacks: tuple

    def format(self) -> str:
        lines = [f"[{self.kind}] {self.message} (thread {self.thread})"]
        for label, frames in self.stacks:
            lines.append(f"  {label}:")
            lines.extend(f"    {f}" for f in frames)
        return "\n".join(lines)


def _stack(skip: int = 2) -> tuple:
    """Cheap acquisition stack: a frame walk, newest first, own-module
    frames skipped via ``skip`` (``traceback`` costs 10x as much and
    this runs on every sanitized acquire)."""
    try:
        f = sys._getframe(skip)
    except ValueError:  # pragma: no cover — shallow stack
        return ()
    out = []
    while f is not None and len(out) < _STACK_LIMIT:
        code = f.f_code
        out.append(f"{code.co_filename}:{f.f_lineno} in {code.co_name}")
        f = f.f_back
    return tuple(out)


class _Hold:
    """One entry of a thread's held-lock stack."""

    __slots__ = ("lock", "count", "t0", "stack")

    def __init__(self, lock, t0, stack):
        self.lock = lock
        self.count = 1
        self.t0 = t0
        self.stack = stack


class _State:
    """The sanitizer: the global lock-order graph, the violation
    ledger, and the per-thread held stacks."""

    def __init__(self, mode: str):
        if mode not in ("raise", "warn"):
            raise ValueError(f"mode must be 'raise' or 'warn', got {mode!r}")
        self.mode = mode
        # Deliberately a RAW lock (the one allowlisted construction
        # site): the graph mutex must be invisible to itself.
        self._mu = threading.Lock()
        self.adj: dict[int, set[int]] = {}        # uid -> successors
        # (a, b) -> (a_name, b_name, a_hold_stack, b_acquire_stack),
        # recorded at first observation of "b acquired while a held".
        self.edges: dict[tuple, tuple] = {}
        self.seen_locks: set[int] = set()
        self.violations: list[Violation] = []
        self._tls = threading.local()

    # ------------------------------------------------------ per-thread

    def holds(self) -> list:
        h = getattr(self._tls, "holds", None)
        if h is None:
            h = self._tls.holds = []
        return h

    def in_hook(self) -> bool:
        return getattr(self._tls, "hook", False)

    # ----------------------------------------------------------- graph

    def _reaches(self, src: int, dst: int) -> bool:
        """DFS: is ``dst`` reachable from ``src`` in the order graph?"""
        stack, seen = [src], set()
        while stack:
            n = stack.pop()
            if n == dst:
                return True
            if n in seen:
                continue
            seen.add(n)
            stack.extend(self.adj.get(n, ()))
        return False

    def record(self, kind: str, message: str, stacks: tuple) -> "Violation":
        v = Violation(kind=kind, message=message,
                      thread=threading.current_thread().name,
                      stacks=stacks)
        with self._mu:
            self.violations.append(v)
        return v

    def report(self, kind: str, message: str, stacks: tuple) -> None:
        v = self.record(kind, message, stacks)
        if self.mode == "raise" or kind == "double-acquire":
            raise LockOrderViolation(kind, v.format())


_SAN: _State | None = None


class _TracedLockBase:
    """The instrumented lock (only ever constructed while the
    sanitizer is enabled — the factories return raw stdlib locks
    otherwise).  Drop-in for ``threading.Lock``/``RLock``: acquire/
    release/locked/context manager."""

    _reentrant = False

    def __init__(self, name: str | None = None):
        self._inner = (threading.RLock() if self._reentrant
                       else threading.Lock())
        self.name = name or ("rlock" if self._reentrant else "lock")
        self._uid = next(_UIDS)

    def __repr__(self):
        kind = "TracedRLock" if self._reentrant else "TracedLock"
        return f"<{kind} {self.name!r} uid={self._uid}>"

    # ------------------------------------------------------- acquire

    def acquire(self, blocking: bool = True, timeout: float = -1):
        st = _SAN
        if st is None or st.in_hook():
            return self._inner.acquire(blocking, timeout)
        holds = st.holds()
        mine = next((h for h in holds if h.lock is self), None)
        if mine is not None and not self._reentrant:
            # Proceeding would block this thread forever: report AND
            # raise (even in warn mode), instead of deadlocking.
            st.report(
                "double-acquire",
                f"non-reentrant lock {self.name!r} re-acquired by its "
                "owning thread — this would deadlock",
                (("first acquisition", mine.stack),
                 ("re-acquisition", _stack())))
        # Only an UNBOUNDED blocking acquire can deadlock, so only it
        # participates in the order graph: a try-acquire / bounded
        # wait is the standard deadlock-AVOIDANCE idiom — raising on
        # its "inverted" order, or recording an edge for an attempt
        # that may never hold both locks, would fabricate violations
        # for code that is correct by construction.
        unbounded = blocking and timeout == -1
        if mine is None and unbounded:
            self._check_order(st, holds)
        t0 = time.perf_counter()
        contended = False
        if unbounded:
            got = self._inner.acquire(False)
            if not got:
                contended = True
                got = self._inner.acquire()
        else:
            got = self._inner.acquire(blocking, timeout)
        if not got:
            return False
        if mine is not None:
            mine.count += 1
            return True
        st.seen_locks.add(self._uid)  # set.add is atomic under the GIL
        holds.append(_Hold(self, time.perf_counter(), _stack()))
        # Edges are committed only AFTER the acquire succeeded (and
        # only for unbounded acquires, per the above).
        if unbounded:
            self._commit_edges(st, holds)
        if contended:
            self._observe(st, "lock.wait_s", time.perf_counter() - t0)
        return True

    def _check_order(self, st: _State, holds: list) -> None:
        """Pre-acquire cycle check against held -> self edges (read
        only — nothing is recorded until the acquire SUCCEEDS, see
        :meth:`_commit_edges`): an acquisition that would close a
        cycle is a lock-order inversion (potential deadlock),
        reported before blocking on the inner lock."""
        if not holds:
            return
        me = self._uid
        bad = None
        with st._mu:
            for h in holds:
                a = h.lock._uid
                if (a, me) in st.edges:
                    continue
                if self._reentrant and h.lock is self:
                    continue
                if st._reaches(me, a):
                    prior = st.edges.get((me, a))
                    stacks = [(f"now: {h.lock.name!r} held", h.stack),
                              (f"now: acquiring {self.name!r}",
                               _stack(skip=3))]
                    if prior is not None:
                        stacks.append((
                            f"recorded: {prior[1]!r} acquired while "
                            f"{prior[0]!r} held", prior[3]))
                    # Out of st._mu before the (possible) raise.
                    bad = (h.lock.name, tuple(stacks))
                    break
        if bad is not None:
            st.report(
                "cycle",
                f"lock-order inversion: acquiring {self.name!r} while "
                f"holding {bad[0]!r}, but the opposite order was "
                "already observed — potential deadlock",
                bad[1])

    def _commit_edges(self, st: _State, holds: list) -> None:
        """Record held -> self edges now that the lock is actually
        held.  Re-checks reachability under the mutex: a racing
        thread may have committed the opposite edge between our
        pre-check and now — recording ours anyway would close the
        cycle silently (the ``(a, me) in edges`` fast path would then
        skip every later check on the pair), so that race reports
        here instead."""
        if len(holds) < 2:
            return
        me = self._uid
        mine = holds[-1]
        bad = None
        with st._mu:
            for h in holds[:-1]:
                a = h.lock._uid
                st.seen_locks.add(a)
                if (a, me) in st.edges:
                    continue
                if self._reentrant and h.lock is self:
                    continue
                if st._reaches(me, a):
                    if bad is None:
                        bad = (h.lock.name,
                               ((f"now: {h.lock.name!r} held", h.stack),
                                (f"now: holding {self.name!r}",
                                 mine.stack)))
                    continue
                st.edges[(a, me)] = (h.lock.name, self.name,
                                     h.stack, mine.stack)
                st.adj.setdefault(a, set()).add(me)
        if bad is not None:
            # Record-only: the lock is already held here, so raising
            # would leak the hold out of __enter__.  The ledger (and
            # conftest's violation gate) still surfaces it.
            st.record(
                "cycle",
                f"lock-order inversion: {self.name!r} acquired while "
                f"holding {bad[0]!r}, but the opposite order was "
                "already observed — potential deadlock",
                bad[1])

    # ------------------------------------------------------- release

    def release(self):
        st = _SAN
        if st is None or st.in_hook():
            self._inner.release()
            return
        holds = st.holds()
        mine = next((h for h in reversed(holds) if h.lock is self), None)
        if mine is not None and mine.count > 1:
            mine.count -= 1
            self._inner.release()
            return
        if mine is not None:
            holds.remove(mine)
        self._inner.release()
        if mine is not None:
            self._observe(st, "lock.held_s",
                          time.perf_counter() - mine.t0)

    # --------------------------------------------------------- extras

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    def _observe(self, st: _State, metric: str, value: float) -> None:
        """Held-time/contention histograms into the obs registry.
        Reads ``distkeras_tpu.obs`` off ``sys.modules`` — never
        imports it (no cycle, no framework pull-in under the stub
        loader) — and sets the per-thread hook flag so the registry's
        own sanitized locks don't recurse into instrumentation."""
        obs = sys.modules.get("distkeras_tpu.obs")
        if obs is None:
            return
        try:
            if obs.active() is None:
                return
            st._tls.hook = True
            try:
                obs.observe(metric, value, lock=self.name)
            finally:
                st._tls.hook = False
        except Exception:  # noqa: BLE001 — telemetry must not break locking
            pass


class _TracedLockImpl(_TracedLockBase):
    _reentrant = False


class _TracedRLockImpl(_TracedLockBase):
    _reentrant = True


def TracedLock(name: str | None = None):  # noqa: N802 — factory, like threading.Lock
    """A mutex for the threaded core modules.  Sanitizer off: returns
    a RAW ``threading.Lock`` (the fast path is exactly the stdlib
    lock — zero wrapper overhead).  Sanitizer on: an instrumented
    lock participating in order/double-acquire checking, labeled
    ``name`` in reports and histograms."""
    if _SAN is None:
        return threading.Lock()
    return _TracedLockImpl(name)


def TracedRLock(name: str | None = None):  # noqa: N802 — factory
    """Reentrant variant of :func:`TracedLock` (same-thread nesting is
    legal and recorded once per outermost hold)."""
    if _SAN is None:
        return threading.RLock()
    return _TracedRLockImpl(name)


def assert_unlocked(site: str) -> None:
    """Guard for subscriber/callback fire sites: the calling thread
    must hold NO sanitized lock — a callback invoked under a lock can
    re-enter the subsystem and deadlock (the PR-8 ``slo.breach``
    subscriber shape).  No-op when the sanitizer is off."""
    st = _SAN
    if st is None or st.in_hook():
        return
    holds = st.holds()
    if not holds:
        return
    names = [h.lock.name for h in holds]
    st.report(
        "held-in-callback",
        f"{site}: callback fired while holding lock(s) {names} — "
        "release before invoking user code",
        tuple((f"{h.lock.name!r} acquired", h.stack) for h in holds))


# ------------------------------------------------------------- control


def enable_sanitizer(mode: str = "raise") -> None:
    """Turn the sanitizer on (idempotent — an already-running window
    keeps its graph).  Locks constructed from now on are instrumented;
    locks that already exist stay raw."""
    global _SAN
    if _SAN is None:
        _SAN = _State(mode)


def disable_sanitizer() -> None:
    """Turn the sanitizer off and drop its graph/ledger.  Locks it
    instrumented keep working (they just stop checking)."""
    global _SAN
    _SAN = None


def sanitizer_enabled() -> bool:
    return _SAN is not None


def violations() -> list:
    """Snapshot of every recorded :class:`Violation` this window."""
    st = _SAN
    if st is None:
        return []
    with st._mu:
        return list(st.violations)


def violation_count() -> int:
    st = _SAN
    return len(st.violations) if st is not None else 0


def clear_violations() -> None:
    st = _SAN
    if st is not None:
        with st._mu:
            st.violations.clear()


def lock_report() -> dict:
    """Small JSON-able summary for timelines (the chaos ladder emits
    one per host): instrumented-lock count, order-graph edge count,
    violation count."""
    st = _SAN
    if st is None:
        return {"enabled": False, "locks": 0, "edges": 0,
                "violations": 0}
    with st._mu:
        return {"enabled": True, "locks": len(st.seen_locks),
                "edges": len(st.edges),
                "violations": len(st.violations)}


_env = os.environ.get("DKT_LOCK_SANITIZER", "").strip().lower()
if _env in ("1", "true", "on", "raise"):
    enable_sanitizer("raise")
elif _env == "warn":
    enable_sanitizer("warn")
del _env


__all__ = ["TracedLock", "TracedRLock", "LockOrderViolation",
           "Violation", "assert_unlocked", "enable_sanitizer",
           "disable_sanitizer", "sanitizer_enabled", "violations",
           "violation_count", "clear_violations", "lock_report"]
