"""Tracing & step timing (SURVEY.md §5: reference has `training_time` only).

The reference's entire observability surface is one wall-clock number
recorded by ``Trainer.train`` (reference: distkeras/trainers.py) plus
whatever the Spark UI shows.  Here:

* :func:`trace` — context manager writing an XLA/TPU profile (HLO
  timelines, per-op HBM/MXU utilization) viewable in TensorBoard or
  Perfetto, via ``jax.profiler``.
* :class:`StepTimer` — cheap per-step wall-clock stats with correct
  device synchronization at the measurement boundaries only (never
  inside the loop, which would stall the TPU pipeline).
* :func:`annotate` — named region that shows up on the profile
  timeline (``jax.profiler.TraceAnnotation``).
"""

from __future__ import annotations

import contextlib
import statistics
import time

import jax

from distkeras_tpu import obs


@contextlib.contextmanager
def trace(logdir: str):
    """Profile everything in the block into ``logdir``.

    View with ``tensorboard --logdir`` (profile plugin) or upload the
    ``.trace.json.gz`` to Perfetto.
    """
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region on the profiler timeline (usable as ctx or decorator)."""
    return jax.profiler.TraceAnnotation(name)


class StepTimer:
    """Wall-clock stats over repeated step calls.

    Usage::

        timer = StepTimer()
        with timer.round():           # sync boundary outside the loop
            for batch in batches:
                state, loss = step(state, *batch)
        timer.finalize(state)         # blocks, closes the open round
        timer.mean_step_s, timer.p50_round_s, timer.samples_per_sec(n)

    Device work is async: individual step dispatches return immediately,
    so per-call timing lies.  The timer therefore measures *rounds*
    (sync → work → sync) and divides by the step count you report.

    **Named phase counters** (``phase``/``phase_s``/``phase_stats``)
    accumulate host wall time per phase across the run — the
    distributed trainers record ``"h2d"`` (host-side batch staging +
    transfer dispatch) and ``"step"`` (the jitted
    reduce-scatter+update+gather dispatch), so an input-bound run is
    distinguishable from a compute-bound one without a profiler.  The
    *device-side* split of a step — reduce vs update vs gather — is by
    design not host-observable (overlap means those regions interleave
    on the timeline); the ZeRO-1 update tags them with
    ``jax.named_scope`` (``zero1/reduce_scatter``, ``zero1/update``,
    ``zero1/all_gather``) so :func:`trace` profiles show the overlap,
    and ``scripts/bench_suite.py zero1_update`` measures the update
    phase as a number.

    The timer is also the **span frontend of the obs subsystem**
    (``distkeras_tpu.obs``, docs/observability.md): with a telemetry
    session active, every ``phase`` block is recorded as a trace span
    ``{scope}.{name}`` and every closed round as a ``{scope}.round``
    event, so a whole run's phase timeline reconstructs offline via
    ``scripts/obs_report.py``.  Disabled (the default), the obs hooks
    are a module-attr ``is None`` check — the timer stays hot-loop
    cheap either way.

    State persists across rounds but NOT across runs: call
    :meth:`reset` at the start of each run (the trainers do, at the
    top of every ``train()``), so ``phase_stats`` always describes the
    run just measured instead of silently accumulating across
    ``train()`` calls.
    """

    def __init__(self, scope: str = "train"):
        self.scope = scope
        self.rounds: list[tuple[float, int]] = []  # (seconds, n_steps)
        self.phases: dict[str, tuple[float, int]] = {}  # name -> (s, calls)
        self._t0: float | None = None
        self._n = 0

    def reset(self) -> None:
        """Drop all recorded rounds and phase stats (fresh run).  Any
        open round is abandoned, not recorded."""
        self.rounds = []
        self.phases = {}
        self._t0 = None
        self._n = 0

    @contextlib.contextmanager
    def phase(self, name: str):
        """Accumulate host wall time under ``name`` (re-entrant safe to
        nest *different* names; never syncs the device — wrap dispatch
        sites, then ``finalize`` closes the round with one barrier).
        Doubles as an obs trace span when telemetry is enabled."""
        t0 = time.perf_counter()
        try:
            if obs.active() is None:  # keep the disabled path
                yield self           # allocation-free (no f-string)
            else:
                with obs.span(f"{self.scope}.{name}"):
                    yield self
        finally:
            dt = time.perf_counter() - t0
            s, c = self.phases.get(name, (0.0, 0))
            self.phases[name] = (s + dt, c + 1)
            if obs.active() is not None:
                # Per-phase latency histogram (e.g. ``train.step_s``):
                # what the rolling-window SLO engine diffs for a LIVE
                # step-time percentile, where the spans above only
                # reconstruct offline.
                obs.observe(f"{self.scope}.{name}_s", dt)

    def phase_s(self, name: str) -> float:
        """Total seconds accumulated under ``name`` (0.0 if unused)."""
        return self.phases.get(name, (0.0, 0))[0]

    def phase_stats(self) -> dict:
        """``{name: {"total_s", "calls", "mean_s"}}`` for every phase."""
        return {name: {"total_s": s, "calls": c,
                       "mean_s": s / c if c else 0.0}
                for name, (s, c) in self.phases.items()}

    @contextlib.contextmanager
    def round(self, n_steps: int = 0):
        self._t0 = time.perf_counter()
        self._n = n_steps
        yield self
        # finalize() closes the round after the caller syncs.

    def count(self, n: int = 1) -> None:
        self._n += n

    def finalize(self, *sync_refs) -> None:
        """Block on ``sync_refs`` (device arrays) and close the round."""
        if sync_refs:
            jax.block_until_ready(sync_refs)
        if self._t0 is not None:
            dur = time.perf_counter() - self._t0
            self.rounds.append((dur, self._n))
            obs.event(f"{self.scope}.round", dur_s=dur, n_steps=self._n)
            self._t0 = None
            self._n = 0

    # ------------------------------------------------------------- stats

    @property
    def total_s(self) -> float:
        return sum(s for s, _ in self.rounds)

    @property
    def total_steps(self) -> int:
        return sum(n for _, n in self.rounds)

    @property
    def mean_step_s(self) -> float:
        n = self.total_steps
        return self.total_s / n if n else 0.0

    @property
    def p50_round_s(self) -> float:
        return statistics.median(s for s, _ in self.rounds) if self.rounds else 0.0

    def samples_per_sec(self, samples_per_step: int) -> float:
        return (samples_per_step * self.total_steps / self.total_s
                if self.total_s else 0.0)
