"""Small array helpers mirroring reference distkeras/utils.py extras."""

from __future__ import annotations

import numpy as np


def to_dense_vector(label, num_classes: int) -> np.ndarray:
    """Integer label -> one-hot dense vector.

    Reference parity: distkeras/utils.py::to_dense_vector.  Vectorized:
    accepts a scalar or an array of labels.
    """
    labels = np.asarray(label, dtype=np.int64)
    return np.eye(num_classes, dtype=np.float32)[labels]


def uniform_weights(model, bounds=(-0.5, 0.5), seed: int | None = None):
    """Re-initialize every weight of ``model`` uniformly in ``bounds``.

    Reference parity: distkeras/utils.py::uniform_weights.
    """
    rng = np.random.default_rng(seed)
    low, high = bounds
    model.set_weights(
        [rng.uniform(low, high, size=w.shape).astype(w.dtype)
         for w in model.get_weights()])
    return model
