"""Small array helpers mirroring reference distkeras/utils.py extras."""

from __future__ import annotations

import numpy as np


def to_dense_vector(label, num_classes: int) -> np.ndarray:
    """Integer label -> one-hot dense vector.

    Reference parity: distkeras/utils.py::to_dense_vector.  Vectorized:
    accepts a scalar or an array of labels.
    """
    labels = np.asarray(label, dtype=np.int64)
    return np.eye(num_classes, dtype=np.float32)[labels]


def uniform_weights(model, bounds=(-0.5, 0.5), seed: int | None = None):
    """Re-initialize every weight of ``model`` uniformly in ``bounds``.

    Reference parity: distkeras/utils.py::uniform_weights.
    """
    rng = np.random.default_rng(seed)
    low, high = bounds
    model.set_weights(
        [rng.uniform(low, high, size=w.shape).astype(w.dtype)
         for w in model.get_weights()])
    return model


def probe_devices(deadline_s: float = 120.0):
    """``jax.devices()`` with a deadline, on a daemon thread.

    The axon relay's backend init can HANG outright when the device
    tunnel is down; callers that must not stall (the driver entry
    gate, bench.py) probe through this instead.  Returns the device
    list; raises ``TimeoutError`` on a hang or re-raises the probe's
    own error.  The single shared definition — keep hang-mode fixes
    here.
    """
    import threading

    import jax

    found, err = [], []

    def probe():
        try:
            found.extend(jax.devices())
        except Exception as e:  # noqa: BLE001 — surface to the caller
            err.append(e)

    t = threading.Thread(target=probe, name="dkt-device-probe",
                         daemon=True)
    t.start()
    t.join(timeout=deadline_s)
    if t.is_alive():
        raise TimeoutError(
            f"jax device discovery hung >{deadline_s:.0f}s — accelerator "
            "tunnel down?")
    if err:
        raise err[0]
    return found


def probe_device_count_subprocess(deadline_s: float = 15.0) -> int:
    """Device-count probe from a FRESH subprocess with a hard timeout.

    Unlike :func:`probe_devices`, a timed-out probe leaves THIS process
    untouched: the thread probe initializes the backend in-process, so
    after a hang every later ``jax.devices()`` blocks on the same init
    lock, while a killed subprocess costs nothing.  Use this first when
    the platform may be a remote tunnel; call :func:`probe_devices`
    in-process only after it answers.  Raises ``TimeoutError`` on a
    hang, ``RuntimeError`` on a failed probe.
    """
    import subprocess
    import sys

    try:
        out = subprocess.run(
            [sys.executable, "-c", "import jax; print(len(jax.devices()))"],
            capture_output=True, timeout=deadline_s, text=True)
    except subprocess.TimeoutExpired:
        raise TimeoutError(
            f"jax device discovery hung >{deadline_s:.0f}s — accelerator "
            "tunnel down?") from None
    if out.returncode == 0 and out.stdout.strip().isdigit():
        return int(out.stdout.strip())
    raise RuntimeError("device probe subprocess failed: "
                       + (out.stderr.strip() or "no output")[-200:])


def nll_to_perplexity(mean_nll: float) -> float:
    """exp(mean NLL) with the overflow guard — the ONE definition of
    the perplexity formula (LMTrainer's eval hook and
    PerplexityEvaluator must stay numerically identical)."""
    import math

    return math.exp(mean_nll) if mean_nll < 700 else float("inf")
