"""Keras model (de)serialization.

Keeps the contract of the reference's ``distkeras/utils.py``
(``serialize_keras_model`` / ``deserialize_keras_model``: architecture as a
JSON string plus a list of weight arrays) so that models travel as plain
picklable dicts — across processes, into checkpoints, and between rounds.
The reference shipped these dicts through Spark closures and TCP sockets;
here they feed process-local reconstruction and orbax checkpoints instead,
but the format stays a ``{"model": json, "weights": [np.ndarray]}`` dict.
"""

from __future__ import annotations

import numpy as np


def serialize_keras_model(model) -> dict:
    """Serialize a Keras model to a picklable dict.

    Reference parity: distkeras/utils.py::serialize_keras_model (JSON
    architecture + weight list).
    """
    return {
        "model": model.to_json(),
        "weights": [np.asarray(w) for w in model.get_weights()],
    }


def deserialize_keras_model(blob: dict):
    """Rebuild a Keras model from :func:`serialize_keras_model` output.

    Reference parity: distkeras/utils.py::deserialize_keras_model.
    """
    import keras

    model = keras.models.model_from_json(blob["model"])
    model.set_weights(blob["weights"])
    return model
