"""Keras model (de)serialization.

Keeps the contract of the reference's ``distkeras/utils.py``
(``serialize_keras_model`` / ``deserialize_keras_model``: architecture as a
JSON string plus a list of weight arrays) so that models travel as plain
picklable dicts — across processes, into checkpoints, and between rounds.
The reference shipped these dicts through Spark closures and TCP sockets;
here they feed process-local reconstruction and orbax checkpoints instead,
but the format stays a ``{"model": json, "weights": [np.ndarray]}`` dict.
"""

from __future__ import annotations

import numpy as np


def serialize_keras_model(model) -> dict:
    """Serialize a Keras model to a picklable dict.

    Reference parity: distkeras/utils.py::serialize_keras_model (JSON
    architecture + weight list).
    """
    return {
        "model": model.to_json(),
        "weights": [np.asarray(w) for w in model.get_weights()],
    }


def deserialize_keras_model(blob: dict):
    """Rebuild a Keras model from :func:`serialize_keras_model` output.

    Reference parity: distkeras/utils.py::deserialize_keras_model.
    """
    import keras

    model = keras.models.model_from_json(blob["model"])
    model.set_weights(blob["weights"])
    return model


def save_lm(path: str, params, cfg) -> None:
    """Persist a transformer LM (params pytree + TransformerConfig) to
    one ``.npz`` — the LM-flagship analogue of
    :func:`serialize_keras_model` (architecture + weights in one
    artifact; orbax checkpoints cover mid-training state, this covers
    shipping a finished model).

    Full-precision trees only — quantize after loading
    (models/quant.quantize_params) since int8 conversion is cheap and
    one-way.
    """
    import dataclasses
    import json

    from distkeras_tpu.models.quant import QTensor

    import jax

    # is_leaf: QTensor is itself a pytree node, so a plain flatten
    # would silently decompose it into its q/s arrays.
    flat, _ = jax.tree_util.tree_flatten_with_path(
        params, is_leaf=lambda x: isinstance(x, QTensor))
    if any(isinstance(v, QTensor) for _, v in flat):
        raise ValueError(
            "save_lm takes the full-precision tree; quantize after "
            "load_lm instead (int8 conversion is cheap and lossy)")
    arrays = {}
    for keypath, leaf in flat:
        name = "/".join(str(getattr(k, "key", k)) for k in keypath)
        arrays[name] = np.asarray(leaf)
    np.savez(path, __config__=json.dumps(dataclasses.asdict(cfg)),
             **arrays)


def load_lm(path: str):
    """Load :func:`save_lm` output; returns ``(params, cfg)``.

    Params come back as host numpy — place them on a mesh with
    ``ShardingPlan.tree_shardings`` + ``device_put`` (or hand them to a
    trainer / jitted ``generate``, which will place them).
    """
    import json

    from distkeras_tpu.models.transformer import TransformerConfig

    data = np.load(path, allow_pickle=False)
    cfg = TransformerConfig(**json.loads(str(data["__config__"])))
    params: dict = {}
    for name in data.files:
        if name == "__config__":
            continue
        node = params
        parts = name.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        # Stays host numpy on purpose: committing to the default device
        # here would OOM exactly the models whose mesh placement the
        # caller needs to control.
        node[parts[-1]] = data[name]
    return params, cfg
