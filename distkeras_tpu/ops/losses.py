"""Functional, jit-friendly losses.

The reference passes Keras loss *names* straight into ``model.compile``
(reference: distkeras/trainers.py ``loss`` kwarg; workers compile with it
before ``train_on_batch``).  Here losses are pure ``f(y_true, y_pred) ->
scalar`` jnp functions so the whole train step stays traceable; the same
reference-era string names are accepted.
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp

Loss = Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray]


def _categorical_crossentropy(y_true, y_pred, from_logits=True):
    import jax.nn

    logp = jax.nn.log_softmax(y_pred, axis=-1) if from_logits else jnp.log(
        jnp.clip(y_pred, 1e-7, 1.0))
    return -jnp.mean(jnp.sum(y_true * logp, axis=-1))


def _sparse_categorical_crossentropy(y_true, y_pred, from_logits=True):
    import jax.nn

    logp = jax.nn.log_softmax(y_pred, axis=-1) if from_logits else jnp.log(
        jnp.clip(y_pred, 1e-7, 1.0))
    y_true = y_true.astype(jnp.int32)
    picked = jnp.take_along_axis(logp, y_true[..., None], axis=-1)[..., 0]
    return -jnp.mean(picked)


def _align(y_true, y_pred):
    """Match label rank to prediction rank for elementwise losses.

    (B,) labels vs (B, 1) predictions would otherwise silently
    broadcast to (B, B) and compute garbage.
    """
    if y_true.ndim == y_pred.ndim - 1 and y_pred.shape[-1] == 1:
        return y_true[..., None]
    if y_true.shape != y_pred.shape:
        raise ValueError(
            f"label shape {y_true.shape} incompatible with prediction "
            f"shape {y_pred.shape}")
    return y_true


def _binary_crossentropy(y_true, y_pred, from_logits=True):
    y_true = _align(jnp.asarray(y_true), y_pred).astype(y_pred.dtype)
    if from_logits:
        # Numerically stable BCE-with-logits.
        z, x = y_true, y_pred
        return jnp.mean(jnp.maximum(x, 0) - x * z + jnp.log1p(jnp.exp(-jnp.abs(x))))
    p = jnp.clip(y_pred, 1e-7, 1 - 1e-7)
    return -jnp.mean(y_true * jnp.log(p) + (1 - y_true) * jnp.log(1 - p))


def _mse(y_true, y_pred):
    y_true = _align(jnp.asarray(y_true), y_pred)
    return jnp.mean(jnp.square(y_pred - y_true.astype(y_pred.dtype)))


def _mae(y_true, y_pred):
    y_true = _align(jnp.asarray(y_true), y_pred)
    return jnp.mean(jnp.abs(y_pred - y_true.astype(y_pred.dtype)))


_LOSSES: dict[str, Loss] = {
    "categorical_crossentropy": _categorical_crossentropy,
    "sparse_categorical_crossentropy": _sparse_categorical_crossentropy,
    "binary_crossentropy": _binary_crossentropy,
    "mse": _mse,
    "mean_squared_error": _mse,
    "mae": _mae,
    "mean_absolute_error": _mae,
}


def resolve_loss(loss) -> Loss:
    """Resolve a loss name or callable to ``f(y_true, y_pred) -> scalar``.

    String names follow the Keras/reference convention.  Callables pass
    through unchanged (they must be jit-traceable).

    Note: crossentropy losses here expect *logits* (models in the zoo end
    in a linear layer); this is both the numerically stable and the
    TPU-friendly convention since XLA fuses the log-softmax into the loss.
    """
    if callable(loss):
        return loss
    try:
        return _LOSSES[loss]
    except KeyError:
        raise ValueError(
            f"Unknown loss {loss!r}; known: {sorted(_LOSSES)} "
            "or pass a callable f(y_true, y_pred) -> scalar.") from None
