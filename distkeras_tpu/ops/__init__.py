from distkeras_tpu.ops.losses import resolve_loss
from distkeras_tpu.ops.optimizers import resolve_optimizer

__all__ = ["resolve_loss", "resolve_optimizer"]
