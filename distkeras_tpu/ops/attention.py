"""Attention kernels: naive, blockwise (online-softmax), and Pallas flash.

The reference has no attention anywhere (its largest model is an LSTM —
reference: examples, IMDB config); this module exists because the TPU
rebuild treats long-context training as first-class.  Three tiers share
one set of semantics so tests can pin them against each other:

- :func:`naive_attention` — O(L^2) materialized logits; the numerics
  oracle for tests.
- :func:`blockwise_attention` — online-softmax over KV chunks
  (`lax.scan`), O(block) memory; pure jnp so it runs on any backend and
  is the differentiable reference for the flash kernel's VJP.  Its
  chunk-update core (:func:`attention_chunk`) is also the per-hop step
  of ring attention (distkeras_tpu.parallel.ring).
- :func:`flash_attention` — Pallas TPU kernel (MXU-tiled, VMEM-resident
  online softmax) on TPU backends; falls back to blockwise elsewhere.
  On the Pallas path the backward is the FA2 construction (dQ and
  dK/dV kernels rebuilding probabilities per tile from the forward's
  saved log-sum-exp); the fallback backward recomputes through the
  blockwise implementation under ``jax.vjp``.  O(L) residuals either
  way.

All take ``q: [B, Lq, H, D]``, ``k/v: [B, Lkv, H, D]`` and return
``[B, Lq, H, D]``.  ``q_offset``/``kv_offset`` give the global positions
of the local chunks so causal masking works when sequences are sharded
(ring attention).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

# Finite stand-in for -inf: keeps exp()/max() NaN-free when a whole row
# or chunk is masked (e.g. ring hops entirely in the causal future).
NEG_INF = -1e30


def _scale_for(q, scale):
    return (1.0 / math.sqrt(q.shape[-1])) if scale is None else scale


def _causal_mask(lq: int, lk: int, q_offset, kv_offset, window=None):
    """[lq, lk] bool mask: True where q position >= k position (global);
    with ``window`` also requires q - k < window (causal sliding
    window: each query sees its last ``window`` positions, self
    included)."""
    rows = jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 0) + q_offset
    cols = jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 1) + kv_offset
    mask = rows >= cols
    if window is not None:
        mask = mask & (rows - cols < window)
    return mask


def _check_window(window, causal) -> None:
    if window is None:
        return
    if not causal:
        raise ValueError(
            "window (sliding-window attention) requires causal=True — "
            "the window is defined over the causal past")
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")


def naive_attention(q, k, v, causal: bool = False, scale: float | None = None,
                    q_offset: int = 0, kv_offset: int = 0,
                    window: int | None = None, segment_ids=None):
    """Materialized-logits attention; the test oracle.

    ``segment_ids [B, L]`` (packed sequences): positions attend only
    within their own segment — the mask composes with causal/window.
    """
    _check_window(window, causal)
    scale = _scale_for(q, scale)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        mask = _causal_mask(q.shape[1], k.shape[1], q_offset, kv_offset,
                            window)
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    if segment_ids is not None:
        seg = segment_ids[:, None, :, None] == segment_ids[:, None, None, :]
        logits = jnp.where(seg, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


# --------------------------------------------------------------- online core


def attention_chunk(q, k, v, m, l, o, causal: bool, scale: float,
                    q_offset, kv_offset, window: int | None = None,
                    seg_q=None, seg_k=None):
    """One online-softmax update with a KV chunk.

    Running state (per q row): ``m`` max logit ``[B,H,Lq]``, ``l``
    normalizer ``[B,H,Lq]``, ``o`` unnormalized output ``[B,H,Lq,D]``.
    This is the flash-attention recurrence; ring attention replays it
    once per hop with the offsets of whichever shard's KV it holds.
    ``seg_q [B, Lq]`` / ``seg_k [B, Lk]``: segment (packed-document)
    masking — cross-segment pairs are dead.
    """
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        mask = _causal_mask(q.shape[1], k.shape[1], q_offset, kv_offset,
                            window)
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    if seg_q is not None:
        seg = seg_q[:, None, :, None] == seg_k[:, None, None, :]
        logits = jnp.where(seg, logits, NEG_INF)
    m_new = jnp.maximum(m, logits.max(axis=-1))
    correction = jnp.exp(m - m_new)
    p = jnp.exp(logits - m_new[..., None])
    l_new = l * correction + p.sum(axis=-1)
    o_new = o * correction[..., None] + jnp.einsum("bhqk,bkhd->bhqd", p, v)
    return m_new, l_new, o_new


def online_init(batch, heads, lq, dim, dtype=jnp.float32):
    """Fresh (m, l, o) state for the online-softmax recurrence."""
    return (jnp.full((batch, heads, lq), NEG_INF, dtype),
            jnp.zeros((batch, heads, lq), dtype),
            jnp.zeros((batch, heads, lq, dim), dtype))


def online_finish(m, l, o):
    """Normalize accumulated output -> [B, Lq, H, D].

    Fully-masked rows return the uniform average of V — identical to
    softmax over an all-``NEG_INF`` row, i.e. exactly what the naive
    oracle computes (finite NEG_INF keeps every tier NaN-free and
    mutually consistent).  The ``l == 0`` guard only protects against
    catastrophic exp-underflow, not the masked case.
    """
    out = o / jnp.where(l == 0, 1.0, l)[..., None]
    return out.transpose(0, 2, 1, 3)


def blockwise_attention(q, k, v, causal: bool = False,
                        scale: float | None = None, block_k: int = 512,
                        q_offset: int = 0, kv_offset: int = 0,
                        window: int | None = None, segment_ids=None):
    """Online-softmax attention scanning KV in chunks; O(block_k) logits.

    Pure jnp: the differentiable any-backend reference for
    :func:`flash_attention`, and the single-device semantics that ring
    attention distributes.  ``segment_ids [B, L]`` masks attention to
    within-segment pairs (packed sequences); requires lq == lkv.
    """
    _check_window(window, causal)
    b, lq, h, d = q.shape
    lk = k.shape[1]
    # Clamp to the largest divisor of lk <= block_k so any length works
    # (e.g. lk=1000 -> 500).  Prime lk degenerates to block_k=1 — pick
    # a composite sequence length if that matters.
    block_k = min(block_k, lk)
    while lk % block_k:
        block_k -= 1
    scale = _scale_for(q, scale)
    n_blocks = lk // block_k
    # [n, B, block, H, D] chunk-major for lax.scan.
    ks = k.reshape(b, n_blocks, block_k, h, d).transpose(1, 0, 2, 3, 4)
    vs = v.reshape(b, n_blocks, block_k, h, d).transpose(1, 0, 2, 3, 4)
    if segment_ids is not None:
        if segment_ids.shape != (b, lk) or lq != lk:
            raise ValueError(
                f"segment_ids must be [batch, seq] = ({b}, {lk}) with "
                f"lq == lkv, got {segment_ids.shape} (lq={lq})")
        segs = segment_ids.reshape(b, n_blocks, block_k).transpose(1, 0, 2)
    else:
        segs = jnp.zeros((n_blocks, b, 1), jnp.int32)  # unused
    qf = q.astype(jnp.float32)

    def body(carry, chunk):
        m, l, o = carry
        kc, vc, sc, idx = chunk
        m, l, o = attention_chunk(
            qf, kc.astype(jnp.float32), vc.astype(jnp.float32), m, l, o,
            causal, scale, q_offset, kv_offset + idx * block_k, window,
            seg_q=None if segment_ids is None else segment_ids,
            seg_k=None if segment_ids is None else sc)
        return (m, l, o), None

    (m, l, o), _ = jax.lax.scan(
        body, online_init(b, h, lq, d),
        (ks, vs, segs, jnp.arange(n_blocks)))
    return online_finish(m, l, o).astype(q.dtype)


# ------------------------------------------------------------- Pallas kernel


def _flash_kernel(q_ref, k_ref, v_ref, *refs, causal: bool, scale: float,
                  with_lse: bool, window: int | None = None,
                  segmented: bool = False):
    """Flash-attention forward for one (batch*head, q-block, kv-block) cell.

    KV streams through the grid's innermost dimension so VMEM holds only
    one [block_k, D] tile at a time — sequence length is HBM-bound, not
    VMEM-bound.  Online-softmax state (m, l, acc) lives in VMEM scratch,
    which persists across the sequential kv-block iterations; it is
    initialized at j == 0 and the normalized output is written at the
    last j.  ``m``/``l`` are stored lane-broadcast ([block_q, 128]) to
    respect the f32 (8, 128) tile.

    With ``with_lse`` (the training path) it also writes the per-row
    log-sum-exp (``lse = m + log l``), the residual the FA2-style
    backward kernels need to rebuild softmax probabilities tile-by-tile
    without O(L^2) memory; inference omits the output (and its HBM
    writes) entirely.

    ``segmented``: two extra int32 inputs (q/k segment-id tiles) gate
    the logits to within-segment pairs — packed-document masking.
    """
    if segmented:
        qseg_ref, kseg_ref, *refs = refs
    if with_lse:
        o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
    else:
        o_ref, m_scr, l_scr, acc_scr = refs
    j = pl.program_id(2)
    n_kb = pl.num_programs(2)
    block_q = q_ref.shape[1]
    block_k = k_ref.shape[1]

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Causal: kv blocks strictly above the diagonal contribute nothing;
    # predicate the whole update away (restores the ~2x causal saving).
    # A window switches to a BANDED grid (see _banded_kv): the inner
    # dimension walks only the ~window/block_k blocks inside the
    # lookback, so K/V HBM traffic — not just compute — is O(window).
    row0 = pl.program_id(1) * block_q
    if window is None:
        col0 = j * block_k
        live = (not causal) or (col0 <= row0 + block_q - 1)
    else:
        col0, live = _banded_cols(row0, j, n_kb, block_q, block_k, window)

    @pl.when(live)
    def _update():
        qi = jax.lax.convert_element_type(q_ref[0], jnp.float32) * scale
        kj = jax.lax.convert_element_type(k_ref[0], jnp.float32)
        vj = jax.lax.convert_element_type(v_ref[0], jnp.float32)
        logits = jax.lax.dot_general(
            qi, kj, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [block_q, block_k]
        if causal:
            logits = jnp.where(_keep_mask(logits.shape, row0, col0, window),
                               logits, NEG_INF)
        if segmented:
            logits = jnp.where(
                qseg_ref[0][:, None] == kseg_ref[0][None, :],
                logits, NEG_INF)
        m = m_scr[:, :1]
        l = l_scr[:, :1]
        m_new = jnp.maximum(m, logits.max(axis=-1, keepdims=True))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(logits - m_new)
        l_new = l * corr + p.sum(axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p, vj, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == n_kb - 1)
    def _finish():
        l = l_scr[:, :1]
        out = acc_scr[:] / jnp.where(l == 0, 1.0, l)
        o_ref[0] = out.astype(o_ref.dtype)
        if with_lse:
            # Lane-broadcast [block_q, 128]: rank-2 (1, block_q) blocks
            # break the TPU (8, 128) tiling; a trailing lane dim is the
            # idiom.
            lse_ref[0] = jnp.broadcast_to(
                m_scr[:, :1] + jnp.log(jnp.where(l == 0, 1.0, l)),
                lse_ref.shape[1:])


try:  # Pallas import is cheap but keep non-TPU environments working.
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    _HAVE_PALLAS = False


def _banded_cols(row0, j, n_inner: int, block_q: int, block_k: int,
                 window: int):
    """(col0, live) for the kv-streaming banded kernels (forward and
    dQ) — the ONE mirror of _banded_kv's index_map: raw < 0 are clamped
    duplicates of block 0 and predicated dead."""
    raw = (row0 + block_q - 1) // block_k - (n_inner - 1) + j
    col0 = jnp.maximum(raw, 0) * block_k
    live = ((raw >= 0)
            & (col0 <= row0 + block_q - 1)
            & (col0 + block_k - 1 >= row0 - (window - 1)))
    return col0, live


def _keep_mask(shape, row0, col0, window):
    """Causal (optionally banded) keep-mask for a [block_q, block_k]
    logits tile at global offsets (row0, col0) — shared by all three
    kernels so forward and backward masks cannot drift."""
    rows = jax.lax.broadcasted_iota(jnp.int32, shape, 0) + row0
    cols = jax.lax.broadcasted_iota(jnp.int32, shape, 1) + col0
    keep = rows >= cols
    if window is not None:
        keep = keep & (rows - cols < window)
    return keep


def _banded_kv(window: int, block_q: int, block_k: int, n_kb: int):
    """Banded inner-grid spec for windowed kernels: (extent, index_map).

    A q block's live kv blocks span floor((row0-window+1)/bk) ..
    floor((row0+bq-1)/bk); the extent bounds that count over any
    alignment, and the map walks them ascending so the last j is the
    diagonal block.  Raw indices below 0 clamp to block 0 and the
    kernels predicate them dead (they would otherwise double-count)."""
    extent = min((window - 1 + block_q - 1) // block_k + 2, n_kb)

    def index_map(bh, i, j):
        last = (i * block_q + block_q - 1) // block_k
        return (bh, jnp.maximum(last - (extent - 1) + j, 0), 0)

    return extent, index_map


def _banded_q(window: int, block_q: int, block_k: int, n_qb: int):
    """Banded inner grid for the dkv kernel (q streams): a kv block's
    live q blocks span floor(col0/bq) .. floor((col0+bk-1+window-1)/bq);
    raw indices above the last block clamp down and are predicated
    dead."""
    extent = min((block_k - 1 + window - 1) // block_q + 2, n_qb)

    def index_map(bh, i, j):
        first = (i * block_k) // block_q
        return (bh, jnp.minimum(first + j, n_qb - 1), 0)

    return extent, index_map


def _flash_pallas(q, k, v, causal, scale, block_q, block_k, interpret=False,
                  with_lse=True, window=None, segment_ids=None):
    """Returns (out, lse) with ``with_lse`` (training), else (out, None) —
    inference skips the lse buffer's HBM writes entirely."""
    b, lq, h, d = q.shape
    lk = k.shape[1]
    block_q, block_k = _require_fit(block_q, lq), _require_fit(block_k, lk)
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, lq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, lk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, lk, d)
    kernel = functools.partial(_flash_kernel, causal=causal, scale=scale,
                               with_lse=with_lse, window=window,
                               segmented=segment_ids is not None)

    o_spec = pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0),
                          memory_space=pltpu.VMEM)
    o_shape = jax.ShapeDtypeStruct((b * h, lq, d), q.dtype)
    lse_spec = pl.BlockSpec((1, block_q, 128), lambda bh, i, j: (bh, i, 0),
                            memory_space=pltpu.VMEM)
    lse_shape = jax.ShapeDtypeStruct((b * h, lq, 128), jnp.float32)
    out_bytes = o_shape.size * q.dtype.itemsize + (
        lse_shape.size * 4 if with_lse else 0)

    n_kb = lk // block_k
    if window is not None:
        inner, kv_map = _banded_kv(window, block_q, block_k, n_kb)
    else:
        inner, kv_map = n_kb, (lambda bh, i, j: (bh, j, 0))

    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), kv_map,
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), kv_map,
                     memory_space=pltpu.VMEM),
    ]
    args = [qf, kf, vf]
    if segment_ids is not None:
        # [B, S] -> [B*H, S] (b-major repeat matches the qf flattening);
        # the kv-side map reuses kv_map's block index, so the banded
        # walk stays in lockstep with the K/V tiles.
        segf = jnp.repeat(segment_ids.astype(jnp.int32), h, axis=0)
        in_specs += [
            pl.BlockSpec((1, block_q),
                         lambda bh, i, j: (bh, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k),
                         lambda bh, i, j: kv_map(bh, i, j)[:2],
                         memory_space=pltpu.VMEM),
        ]
        args += [segf, segf]

    def call(): return pl.pallas_call(
        kernel,
        grid=(b * h, lq // block_q, inner),
        in_specs=in_specs,
        out_specs=(o_spec, lse_spec) if with_lse else o_spec,
        out_shape=(o_shape, lse_shape) if with_lse else o_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # m (lane-broadcast)
            pltpu.VMEM((block_q, 128), jnp.float32),  # l
            pltpu.VMEM((block_q, d), jnp.float32),    # acc
        ],
        cost_estimate=pl.CostEstimate(
            flops=4 * b * h * lq * lk * d,
            bytes_accessed=(qf.nbytes + kf.nbytes + vf.nbytes + out_bytes),
            transcendentals=b * h * lq * lk,
        ),
    )(*args)

    if interpret:
        # The TPU-semantics interpreter: validates the kernel (incl.
        # program_id, memory spaces) on CPU in tests.  The mode is
        # captured at pallas_call *construction*, hence the thunk.
        with pltpu.force_tpu_interpret_mode():
            res = call()
    else:
        res = call()
    out, lse = res if with_lse else (res, None)
    out = out.reshape(b, h, lq, d).transpose(0, 2, 1, 3)
    return out, (lse[:, :, 0] if with_lse else None)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         *refs, causal: bool, scale: float,
                         window: int | None = None,
                         segmented: bool = False):
    """dQ for one (batch*head, q-block, kv-block) cell.

    FA2 backward: probabilities are rebuilt per tile from the saved
    log-sum-exp (p = exp(s - lse)); ``delta = rowsum(dO * O)`` folds the
    softmax normalizer's gradient.  dq accumulates across the inner
    kv-block dimension in VMEM scratch.  Segment masking re-applies to
    the rebuilt logits (masked pairs rebuild p = 0, so their gradient
    contribution vanishes exactly as in the forward).
    """
    if segmented:
        qseg_ref, kseg_ref, dq_ref, dq_scr = refs
    else:
        dq_ref, dq_scr = refs
    j = pl.program_id(2)
    n_kb = pl.num_programs(2)
    block_q = q_ref.shape[1]
    block_k = k_ref.shape[1]
    row0 = pl.program_id(1) * block_q

    @pl.when(j == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    if window is None:
        col0 = j * block_k
        live = (not causal) or (col0 <= row0 + block_q - 1)
    else:
        col0, live = _banded_cols(row0, j, n_kb, block_q, block_k, window)

    @pl.when(live)
    def _update():
        qi = jax.lax.convert_element_type(q_ref[0], jnp.float32)
        kj = jax.lax.convert_element_type(k_ref[0], jnp.float32)
        vj = jax.lax.convert_element_type(v_ref[0], jnp.float32)
        do = jax.lax.convert_element_type(do_ref[0], jnp.float32)
        s = jax.lax.dot_general(qi, kj, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = jnp.where(_keep_mask(s.shape, row0, col0, window),
                          s, NEG_INF)
        if segmented:
            s = jnp.where(qseg_ref[0][:, None] == kseg_ref[0][None, :],
                          s, NEG_INF)
        p = jnp.exp(s - lse_ref[0][:, :1])
        dp = jax.lax.dot_general(do, vj, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0][:, :1]) * scale
        dq_scr[:] += jax.lax.dot_general(
            ds, kj, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == n_kb - 1)
    def _finish():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          *refs, causal: bool,
                          scale: float, window: int | None = None,
                          n_qb_total: int = 0, segmented: bool = False):
    """dK/dV for one (batch*head, kv-block, q-block) cell; q streams on
    the inner grid dimension, accumulating into the kv block's scratch."""
    if segmented:
        qseg_ref, kseg_ref, dk_ref, dv_ref, dk_scr, dv_scr = refs
    else:
        dk_ref, dv_ref, dk_scr, dv_scr = refs
    jq = pl.program_id(2)
    n_qb = pl.num_programs(2)
    block_k = k_ref.shape[1]
    block_q = q_ref.shape[1]
    col0 = pl.program_id(1) * block_k

    @pl.when(jq == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    # Causal: a q block contributes unless entirely above the diagonal;
    # with a window the inner grid is banded (mirror _banded_q): only
    # the q blocks inside this kv block's horizon stream through, and
    # clamped duplicates past the last block are predicated dead.
    if window is None:
        row0 = jq * block_q
        live = (not causal) or (row0 + block_q - 1 >= col0)
    else:
        raw = col0 // block_q + jq
        clamped = jnp.minimum(raw, n_qb_total - 1)
        row0 = clamped * block_q
        live = ((raw <= n_qb_total - 1)
                & (row0 + block_q - 1 >= col0)
                & (row0 - (col0 + block_k - 1) < window))

    @pl.when(live)
    def _update():
        qi = jax.lax.convert_element_type(q_ref[0], jnp.float32)
        kj = jax.lax.convert_element_type(k_ref[0], jnp.float32)
        vj = jax.lax.convert_element_type(v_ref[0], jnp.float32)
        do = jax.lax.convert_element_type(do_ref[0], jnp.float32)
        s = jax.lax.dot_general(qi, kj, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = jnp.where(_keep_mask(s.shape, row0, col0, window),
                          s, NEG_INF)
        if segmented:
            s = jnp.where(qseg_ref[0][:, None] == kseg_ref[0][None, :],
                          s, NEG_INF)
        p = jnp.exp(s - lse_ref[0][:, :1])  # [block_q, block_k]
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, vj, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0][:, :1]) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds, qi, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(jq == n_qb - 1)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_pallas_bwd(q, k, v, out, lse, g, causal, scale, block_q, block_k,
                      interpret=False, window=None, segment_ids=None):
    """Pallas dQ/dK/dV from the saved (out, lse) residuals."""
    b, lq, h, d = q.shape
    lk = k.shape[1]
    block_q, block_k = _require_fit(block_q, lq), _require_fit(block_k, lk)
    flat = lambda a, L: a.transpose(0, 2, 1, 3).reshape(b * h, L, d)
    qf, kf, vf = flat(q, lq), flat(k, lk), flat(v, lk)
    dof, of = flat(g, lq), flat(out, lq)
    delta = jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32), axis=-1)
    # Lane-broadcast row vectors (TPU tiling; see _flash_kernel note).
    lane = lambda a: jnp.broadcast_to(a[:, :, None], (*a.shape, 128))
    lse_l, delta_l = lane(lse), lane(delta)
    segmented = segment_ids is not None
    segf = (jnp.repeat(segment_ids.astype(jnp.int32), h, axis=0)
            if segmented else None)
    # Rank-2 seg specs ride the SAME block index as their rank-3
    # q/k twins ([:2] drops the trailing 0), so banded walks stay in
    # lockstep.
    seg_of = lambda at: ((1, at[0][1]), lambda bh, i, j: at[1](bh, i, j)[:2])

    vspec = lambda f: pl.BlockSpec(*f, memory_space=pltpu.VMEM)
    q_at = ((1, block_q, d), lambda bh, i, j: (bh, i, 0))
    kv_at_inner = ((1, block_k, d), lambda bh, i, j: (bh, j, 0))
    row_at = ((1, block_q, 128), lambda bh, i, j: (bh, i, 0))

    n_kb = lk // block_k
    if window is not None:
        dq_inner, dq_kv_map = _banded_kv(window, block_q, block_k, n_kb)
        kv_at_banded = ((1, block_k, d), dq_kv_map)
    else:
        dq_inner, kv_at_banded = n_kb, kv_at_inner

    def call_dq():
        in_specs = [vspec(q_at), vspec(kv_at_banded), vspec(kv_at_banded),
                    vspec(q_at), vspec(row_at), vspec(row_at)]
        args = [qf, kf, vf, dof, lse_l, delta_l]
        if segmented:
            in_specs += [vspec(seg_of(q_at)), vspec(seg_of(kv_at_banded))]
            args += [segf, segf]
        return pl.pallas_call(
            functools.partial(_flash_bwd_dq_kernel, causal=causal,
                              scale=scale, window=window,
                              segmented=segmented),
            grid=(b * h, lq // block_q, dq_inner),
            in_specs=in_specs,
            out_specs=vspec(q_at),
            out_shape=jax.ShapeDtypeStruct((b * h, lq, d), q.dtype),
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
            cost_estimate=pl.CostEstimate(
                flops=6 * b * h * lq * lk * d,
                bytes_accessed=(qf.nbytes + kf.nbytes + vf.nbytes
                                + dof.nbytes + lse_l.nbytes + delta_l.nbytes),
                transcendentals=b * h * lq * lk),
        )(*args)

    kv_at = ((1, block_k, d), lambda bh, i, j: (bh, i, 0))
    q_at_inner = ((1, block_q, d), lambda bh, i, j: (bh, j, 0))
    row_at_inner = ((1, block_q, 128), lambda bh, i, j: (bh, j, 0))

    n_qb = lq // block_q
    if window is not None:
        dkv_inner, dkv_q_map = _banded_q(window, block_q, block_k, n_qb)
        q_in = ((1, block_q, d), dkv_q_map)
        row_in = ((1, block_q, 128), dkv_q_map)
    else:
        dkv_inner, q_in, row_in = n_qb, q_at_inner, row_at_inner

    def call_dkv():
        in_specs = [vspec(q_in), vspec(kv_at), vspec(kv_at),
                    vspec(q_in), vspec(row_in),
                    vspec(row_in)]
        args = [qf, kf, vf, dof, lse_l, delta_l]
        if segmented:
            in_specs += [vspec(seg_of(q_in)), vspec(seg_of(kv_at))]
            args += [segf, segf]
        return pl.pallas_call(
            functools.partial(_flash_bwd_dkv_kernel, causal=causal,
                              scale=scale, window=window,
                              n_qb_total=n_qb, segmented=segmented),
            grid=(b * h, lk // block_k, dkv_inner),
            in_specs=in_specs,
            out_specs=(vspec(kv_at), vspec(kv_at)),
            out_shape=(jax.ShapeDtypeStruct((b * h, lk, d), k.dtype),
                       jax.ShapeDtypeStruct((b * h, lk, d), v.dtype)),
            scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                            pltpu.VMEM((block_k, d), jnp.float32)],
            cost_estimate=pl.CostEstimate(
                flops=6 * b * h * lq * lk * d,
                bytes_accessed=(qf.nbytes + kf.nbytes + vf.nbytes
                                + dof.nbytes + lse_l.nbytes + delta_l.nbytes),
                transcendentals=b * h * lq * lk),
        )(*args)

    if interpret:
        with pltpu.force_tpu_interpret_mode():
            dq = call_dq()
            dk, dv = call_dkv()
    else:
        dq = call_dq()
        dk, dv = call_dkv()
    unflat = lambda a, L: a.reshape(b, h, L, d).transpose(0, 2, 1, 3)
    return unflat(dq, lq), unflat(dk, lk), unflat(dv, lk)


def _fit_block(requested: int, length: int,
               strict: bool = False) -> int | None:
    """Kernel block size <= ``requested`` that tiles ``length`` exactly.

    The min-clamp alone covers short rows (one block == the row) and
    explicit blocks that already divide the row; otherwise pick the
    largest lane-aligned (x128) divisor of ``length``, so raising the
    tuned defaults never pushes a length that used to tile off the
    Pallas path (e.g. seq 1536 under the (1024, 1024) defaults fits
    768).  None = nothing tiles; the caller falls back to blockwise.

    ``strict`` (explicitly requested blocks): never substitute a
    different divisor — a sweep/benchmark caller asking for block 512
    at length 768 must not silently time a 384-block kernel.  The
    min-clamp still applies (one block == the whole row is the same
    grid point); anything else returns None so the caller takes the
    blockwise fallback, the pre-fitting behavior for such shapes.
    """
    b = min(requested, length)
    if length % b == 0:
        return b
    if strict:
        return None
    return max((c for c in range(128, b + 1, 128) if length % c == 0),
               default=None)


def _require_fit(requested: int, length: int) -> int:
    """_fit_block for the kernel launchers: a grid whose block does not
    divide the length would silently leave tail rows unwritten, so an
    unfittable request is an error, never a clamp."""
    b = _fit_block(requested, length)
    if b is None:
        raise ValueError(
            f"no kernel block <= {requested} tiles sequence length "
            f"{length}; pick a length divisible by 128 or a block that "
            "divides it (flash_attention's fallback handles any length)")
    return b


# Measured optimum of the hardware sweep (docs/perf_transformer.md).
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024


def _pallas_blocks(lq, lk, d, block_q, block_k, gate_small_bk=False,
                   strict_q=False, strict_k=False):
    """Pure tiling/quality decision (backend-independent, unit-tested):
    the fitted (bq, bk) the kernel would launch with, or None for the
    blockwise fallback.  ``strict_*`` marks explicitly requested blocks
    (see _fit_block): honored exactly or not at all."""
    # Tiling constraints: last dim 128-aligned, seq divisible into blocks.
    if d % 128 != 0 or min(lq, lk) < 8:
        return None
    bq = _fit_block(block_q, lq, strict=strict_q)
    bk = _fit_block(block_k, lk, strict=strict_k)
    if bq is None or bk is None:
        return None
    # Defaulted callers only (``gate_small_bk``): tiny fitted KV tiles
    # usually lose to the XLA blockwise fallback end-to-end (sweep,
    # docs/perf_transformer.md: at block_k=128 the kernel is slower
    # than the fallback for every block_q except 1024, which edges it
    # out by ~4%), so keep bk=128 only when bq fitted to >=1024.  An
    # EXPLICIT small block_k is always honored — the sweep itself must
    # be able to time the kernel at any point of its grid.
    if gate_small_bk and bk < 256 and bk != lk and bq < 1024:
        return None
    return bq, bk


def _use_pallas(q, k, block_q, block_k, gate_small_bk=False,
                strict_q=False, strict_k=False) -> bool:
    if not _HAVE_PALLAS or jax.default_backend() != "tpu":
        return False
    return _pallas_blocks(q.shape[1], k.shape[1], q.shape[-1],
                          block_q, block_k, gate_small_bk,
                          strict_q=strict_q, strict_k=strict_k) is not None


def _resolve_blocks(block_q, block_k):
    """None -> tuned default; the small-bk gate and divisor refitting
    apply only to defaulted blocks — explicit blocks are honored
    exactly or fall back (strict _fit_block).  The ONE definition
    shared by flash_attention and its custom_vjp fwd/bwd so primal and
    vjp can never disagree."""
    q_explicit, k_explicit = block_q is not None, block_k is not None
    gate = not k_explicit
    bq = block_q if q_explicit else DEFAULT_BLOCK_Q
    bk = block_k if k_explicit else DEFAULT_BLOCK_K
    return bq, bk, gate, q_explicit, k_explicit


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal: bool = False, scale: float | None = None,
                    block_q: int | None = None, block_k: int | None = None,
                    window: int | None = None, segment_ids=None):
    """Fused attention: Pallas kernel on TPU, blockwise jnp elsewhere.

    Differentiable with O(L) residuals both ways: on the Pallas path
    the backward is the FA2 construction — dQ/dK/dV kernels that
    rebuild probabilities per tile from the forward's saved
    log-sum-exp; on the fallback path the backward re-runs the
    blockwise forward under ``jax.vjp``.

    ``window`` (with ``causal=True``) is sliding-window attention: each
    query attends its last ``window`` positions (self included).  The
    kernels skip kv blocks entirely beyond the lookback, so compute per
    query is O(window), not O(L) — the long-context local-attention
    primitive (Mistral-style).

    ``segment_ids [B, L]`` int32 (packed sequences): attention is
    masked to within-segment pairs on every tier, forward and backward
    — the packed-document training primitive.  An integer input: its
    cotangent is None.

    ``block_q``/``block_k`` default (None) to the measured optimum of
    the (block_q, block_k) hardware sweep on the long-context benchmark
    config (seq 4096, d1024 L8, TPU v5e —
    `scripts/sweep_attention_blocks.py`, results in
    docs/perf_transformer.md): (1024, 1024) beat the untuned (256, 512)
    by 35% on the full train step.  Defaulted blocks are fitted per
    call (``_fit_block``): shorter sequences clamp to one block, and
    lengths the default doesn't divide (e.g. 1536) drop to their
    largest lane-aligned divisor instead of leaving the Pallas path —
    except that a *defaulted* call never fits below a 256 KV tile
    (measured slower than the fallback); pass block_k explicitly to
    force a small-tile kernel.  EXPLICIT blocks are honored exactly:
    a requested block that does not divide the length (beyond the
    whole-row min-clamp) takes the blockwise fallback rather than
    silently launching a different grid point — sweep callers measure
    the block they asked for.
    """
    _check_window(window, causal)
    s = _scale_for(q, scale)
    bq, bk, gate, xq, xk = _resolve_blocks(block_q, block_k)
    if _use_pallas(q, k, bq, bk, gate_small_bk=gate,
                   strict_q=xq, strict_k=xk):
        return _flash_pallas(q, k, v, causal, s, bq, bk,
                             with_lse=False, window=window,
                             segment_ids=segment_ids)[0]
    return blockwise_attention(q, k, v, causal=causal, scale=s,
                               block_k=bk, window=window,
                               segment_ids=segment_ids)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, window=None,
               segment_ids=None):
    _check_window(window, causal)
    s = _scale_for(q, scale)
    bq, bk, gate, xq, xk = _resolve_blocks(block_q, block_k)
    if _use_pallas(q, k, bq, bk, gate_small_bk=gate,
                   strict_q=xq, strict_k=xk):
        out, lse = _flash_pallas(q, k, v, causal, s, bq, bk,
                                 window=window, segment_ids=segment_ids)
        return out, (q, k, v, out, lse, segment_ids)
    out = blockwise_attention(q, k, v, causal=causal, scale=s,
                              block_k=bk, window=window,
                              segment_ids=segment_ids)
    return out, (q, k, v, None, None, segment_ids)


def _flash_bwd(causal, scale, block_q, block_k, window, res, g):
    q, k, v, out, lse, segment_ids = res
    s = _scale_for(q, scale)
    bq, bk, _, _, _ = _resolve_blocks(block_q, block_k)
    if lse is not None:
        dq, dk, dv = _flash_pallas_bwd(q, k, v, out, lse, g, causal, s,
                                       bq, bk, window=window,
                                       segment_ids=segment_ids)
        return dq, dk, dv, None
    _, vjp = jax.vjp(
        lambda q, k, v: blockwise_attention(
            q, k, v, causal=causal, scale=s, block_k=bk,
            window=window, segment_ids=segment_ids),
        q, k, v)
    return (*vjp(g), None)


flash_attention.defvjp(_flash_fwd, _flash_bwd)
