"""Optimizer resolution: reference-style names -> optax transforms.

The reference hands Keras optimizer name strings to ``model.compile``
inside each Spark worker (reference: distkeras/trainers.py
``worker_optimizer`` kwarg).  Here the same names resolve to optax
gradient transformations applied inside the jitted train step, so the
update math runs on-device and fuses with the backward pass.
"""

from __future__ import annotations

import optax

# Every optimizer name this module resolves has a PER-LEAF ELEMENTWISE
# update rule: the step taken for element i depends only on element i's
# gradient/moment history (plus replicated scalars like the step count
# or a global-norm clip factor, which survive sharding as cheap scalar
# psums).  That property is what makes the ZeRO-1 sharded weight update
# (parallel/collectives.zero1_optimizer) *math-identical*: slicing the
# flattened view across replicas commutes with the update.  Transforms
# that mix elements within a leaf — LARS/LAMB per-layer trust ratios,
# Shampoo-style preconditioners — are NOT in this set and would
# silently diverge under zero1.
ZERO1_ELEMENTWISE = frozenset(
    {"sgd", "adam", "adamw", "nadam", "adagrad", "adadelta", "rmsprop"})


def zero1_compatible(spec) -> bool | None:
    """Whether ``spec`` is known-safe under the ZeRO-1 sharded update.

    Returns ``True`` for resolvable names in :data:`ZERO1_ELEMENTWISE`
    (all of them today), ``False`` for known-unsafe names (none yet),
    and ``None`` for anything this module cannot inspect — a prebuilt
    optax transform — meaning "caller must vouch for elementwise
    update math" (the trainers warn).
    """
    if isinstance(spec, str):
        return spec.lower() in ZERO1_ELEMENTWISE
    return None


def resolve_optimizer(spec, learning_rate: float | None = None
                      ) -> optax.GradientTransformation:
    """Resolve ``spec`` to an ``optax.GradientTransformation``.

    ``spec`` may be:
      * a string name: sgd, adam, adamw, adagrad, adadelta, rmsprop, nadam
        (the set the reference's Keras 1/2 accepted for ``worker_optimizer``)
      * an ``optax.GradientTransformation`` (passed through)
    ``learning_rate`` overrides the per-name default (the Keras default).
    It may also be an optax *schedule* — any ``step -> lr`` callable,
    e.g. ``optax.warmup_cosine_decay_schedule(...)`` — which every optax
    factory consumes natively; the schedule evaluates on-device from the
    optimizer's own step count, so warmup/decay live inside the jitted
    train step with no per-step host traffic.  (The reference has no
    schedule support at all — a fixed ``learning_rate`` kwarg per
    trainer, reference: distkeras/trainers.py.)
    """
    if isinstance(spec, optax.GradientTransformation):
        return spec
    if not isinstance(spec, str):
        raise TypeError(
            f"optimizer spec must be a name or optax transform, got {type(spec)}")

    name = spec.lower()
    defaults = {
        "sgd": 0.01,
        "adam": 0.001,
        "adamw": 0.001,
        "nadam": 0.001,
        "adagrad": 0.01,
        "adadelta": 1.0,
        "rmsprop": 0.001,
    }
    if name not in defaults:
        raise ValueError(
            f"Unknown optimizer {spec!r}; known: {sorted(defaults)}")
    lr = learning_rate if learning_rate is not None else defaults[name]
    if not callable(lr) and lr <= 0:
        raise ValueError(f"learning_rate must be positive, got {lr}")
    factory = {
        "sgd": optax.sgd,
        "adam": optax.adam,
        "adamw": optax.adamw,
        "nadam": optax.nadam,
        "adagrad": optax.adagrad,
        "adadelta": optax.adadelta,
        "rmsprop": optax.rmsprop,
    }[name]
    return factory(lr)
