"""Optimizer resolution: reference-style names -> optax transforms.

The reference hands Keras optimizer name strings to ``model.compile``
inside each Spark worker (reference: distkeras/trainers.py
``worker_optimizer`` kwarg).  Here the same names resolve to optax
gradient transformations applied inside the jitted train step, so the
update math runs on-device and fuses with the backward pass.
"""

from __future__ import annotations

import optax

# Every optimizer name this module resolves has a PER-LEAF ELEMENTWISE
# update rule: the step taken for element i depends only on element i's
# gradient/moment history (plus replicated scalars like the step count
# or a global-norm clip factor, which survive sharding as cheap scalar
# psums).  That property is what makes the ZeRO sharded weight update
# (parallel/collectives.py, every stage) *math-identical*: slicing the
# flattened view across replicas commutes with the update.  Transforms
# that mix elements within a leaf — LARS/LAMB per-layer trust ratios,
# Shampoo-style preconditioners — are NOT in this set and would
# silently diverge under a sharded update.
ZERO1_ELEMENTWISE = frozenset(
    {"sgd", "adam", "adamw", "nadam", "adagrad", "adadelta", "rmsprop"})

# optax factory names whose transforms are per-leaf elementwise (plus
# replicated scalars): prebuilt transforms built ONLY from these are
# recognized safe at trainer construction, so e.g. a bare
# ``optax.adam(1e-3)`` no longer draws the can't-verify warning.
_ELEMENTWISE_FACTORIES = frozenset({
    "chain", "named_chain", "masked", "flatten", "identity",
    "with_extra_args_support",
    "scale", "scale_by_learning_rate", "scale_by_schedule",
    "inject_hyperparams",
    "scale_by_adam", "scale_by_amsgrad", "scale_by_adamax",
    "scale_by_lion", "scale_by_rms", "scale_by_stddev", "scale_by_rss",
    "scale_by_belief", "scale_by_yogi", "scale_by_radam",
    "scale_by_adadelta", "scale_by_optimistic_gradient",
    "add_decayed_weights", "trace", "ema", "clip",
    "clip_by_global_norm", "zero_nans", "keep_params_nonnegative",
    "apply_every", "add_noise",
})

# optax factory names KNOWN to mix elements within a leaf (per-layer
# trust ratios, full-matrix/ blocked preconditioners, sign-of-sum
# tricks over the leaf).  A prebuilt transform containing one raises at
# trainer construction, naming it (parallel/collectives.zero_validate).
_NON_ELEMENTWISE_FACTORIES = frozenset({
    "scale_by_trust_ratio",          # LARS / LAMB
    "normalize_by_update_norm",
    "scale_by_sm3", "scale_by_novograd",
    "scale_by_distance_over_gradients",
})


def _factory_names(transform):
    """Best-effort build recipe of a prebuilt transform, as
    ``(factory names, opaque)``: optax factories return closures whose
    ``__qualname__`` is ``"<factory>.<locals>.<fn>"``, and combinators
    (``chain``, ``masked``, the aliases) close over the inner
    transforms' init/update closures — so walking the closure graph
    and collecting the qualname roots recovers the recipe.  ``opaque``
    is True when ANY reachable piece is not a recognizable optax-style
    closure (a module-level function, a non-optax factory, a truncated
    walk) — the caller must then never conclude "safe", only "unsafe"
    (a known-bad name was still found) or "uninspectable".  Returns
    None when even the top-level init/update are unrecognizable."""
    names: set[str] = set()
    opaque = False
    seen: set[int] = set()
    stack = [getattr(transform, "init", None),
             getattr(transform, "update", None)]
    if not all(callable(f) for f in stack):
        return None
    for fn in stack:
        if ".<locals>." not in getattr(fn, "__qualname__", ""):
            return None  # top level unrecognizable: nothing to walk
    def classify(fn):
        """Push a recipe callable, or flip `opaque` if it did not come
        out of optax — a user-written init/update (module-level or
        closure) is exactly the thing the recipe cannot vouch for.
        optax's own module-level helpers (``init_empty_state`` et al.)
        are inert and stay silent."""
        nonlocal opaque
        mod = getattr(fn, "__module__", "") or ""
        if not mod.startswith("optax"):
            # Keep walking its closure anyway: it may still wrap a
            # known-bad optax transform worth naming.
            opaque = True
        stack.append(fn)

    while stack:
        fn = stack.pop()
        if id(fn) in seen:
            continue
        if len(seen) > 256:  # runaway graph: partial recipe only
            opaque = True
            break
        seen.add(id(fn))
        qual = getattr(fn, "__qualname__", "")
        mod = getattr(fn, "__module__", "") or ""
        if ".<locals>." in qual and mod.startswith("optax"):
            names.add(qual.split(".", 1)[0])
        for cell in getattr(fn, "__closure__", None) or ():
            try:
                val = cell.cell_contents
            except ValueError:  # pragma: no cover - empty cell
                continue
            in_tuple = isinstance(val, (tuple, list))
            vals = list(val) if in_tuple else [val]
            for v in vals:
                if hasattr(v, "init") and hasattr(v, "update") \
                        and callable(getattr(v, "init", None)) \
                        and callable(getattr(v, "update", None)):
                    # A nested transform object (masked, wrappers):
                    # BOTH halves must be recognizable factory
                    # closures or the recipe is opaque (classify
                    # flips the flag; the old code silently skipped
                    # them and could conclude "safe" around an
                    # uninspectable inner update).
                    classify(v.init)
                    classify(v.update)
                elif callable(v) and in_tuple:
                    # A tuple of callables in a combinator closure IS
                    # the inner transforms' init/update halves (optax
                    # chain closes over `init_fns`/`update_fns`) —
                    # every member must be recognizable or the recipe
                    # is opaque.
                    classify(v)
                elif callable(v) and ".<locals>." in getattr(
                        v, "__qualname__", ""):
                    # Singleton helper closures (schedules etc.): walk
                    # them for names; module-level helpers are inert.
                    stack.append(v)
    return names, opaque


def zero1_offender(spec) -> str | None:
    """The name of the known non-elementwise optax transform inside
    ``spec``, or None — what :func:`~distkeras_tpu.parallel.
    collectives.zero_validate` puts in its construction-time error so
    the failure is attributable instead of a silent divergence inside
    the scattered update."""
    if isinstance(spec, str):
        return None
    try:
        recipe = _factory_names(spec)
    except Exception:  # pragma: no cover - defensive
        return None
    if recipe is None:
        return None
    names, _opaque = recipe
    bad = sorted(names & _NON_ELEMENTWISE_FACTORIES)
    return bad[0] if bad else None


def zero1_compatible(spec) -> bool | None:
    """Whether ``spec`` is known-safe under the ZeRO sharded update
    (stages 1/2/3 share the elementwise requirement).

    Returns ``True`` for resolvable names in :data:`ZERO1_ELEMENTWISE`
    and for prebuilt optax transforms assembled only from recognized
    elementwise factories; ``False`` for known-unsafe specs — an
    unresolvable name, or a prebuilt transform containing a factory in
    the non-elementwise set (``zero1_offender`` names it); ``None``
    for anything this module cannot inspect, meaning "caller must
    vouch for elementwise update math" (the trainers warn).
    """
    if isinstance(spec, str):
        return spec.lower() in ZERO1_ELEMENTWISE
    try:
        recipe = _factory_names(spec)
    except Exception:  # pragma: no cover - defensive
        return None
    if recipe is None:
        return None
    names, opaque = recipe
    if names & _NON_ELEMENTWISE_FACTORIES:
        return False           # known-bad beats opaque: name it
    if opaque or not names:
        return None            # any unattributable piece: never "safe"
    if names <= _ELEMENTWISE_FACTORIES:
        return True
    return None


def resolve_optimizer(spec, learning_rate: float | None = None
                      ) -> optax.GradientTransformation:
    """Resolve ``spec`` to an ``optax.GradientTransformation``.

    ``spec`` may be:
      * a string name: sgd, adam, adamw, adagrad, adadelta, rmsprop, nadam
        (the set the reference's Keras 1/2 accepted for ``worker_optimizer``)
      * an ``optax.GradientTransformation`` (passed through)
    ``learning_rate`` overrides the per-name default (the Keras default).
    It may also be an optax *schedule* — any ``step -> lr`` callable,
    e.g. ``optax.warmup_cosine_decay_schedule(...)`` — which every optax
    factory consumes natively; the schedule evaluates on-device from the
    optimizer's own step count, so warmup/decay live inside the jitted
    train step with no per-step host traffic.  (The reference has no
    schedule support at all — a fixed ``learning_rate`` kwarg per
    trainer, reference: distkeras/trainers.py.)
    """
    if isinstance(spec, optax.GradientTransformation):
        return spec
    if not isinstance(spec, str):
        raise TypeError(
            f"optimizer spec must be a name or optax transform, got {type(spec)}")

    name = spec.lower()
    defaults = {
        "sgd": 0.01,
        "adam": 0.001,
        "adamw": 0.001,
        "nadam": 0.001,
        "adagrad": 0.01,
        "adadelta": 1.0,
        "rmsprop": 0.001,
    }
    if name not in defaults:
        raise ValueError(
            f"Unknown optimizer {spec!r}; known: {sorted(defaults)}")
    lr = learning_rate if learning_rate is not None else defaults[name]
    if not callable(lr) and lr <= 0:
        raise ValueError(f"learning_rate must be positive, got {lr}")
    factory = {
        "sgd": optax.sgd,
        "adam": optax.adam,
        "adamw": optax.adamw,
        "nadam": optax.nadam,
        "adagrad": optax.adagrad,
        "adadelta": optax.adadelta,
        "rmsprop": optax.rmsprop,
    }[name]
    return factory(lr)
