"""ctypes bindings for the native input-pipeline kernels.

Builds ``native/dataloader.cc`` into a shared library on first use
(g++, cached next to this package) and exposes :func:`gather_rows` /
:func:`gather_normalize_u8`.  Everything degrades to numpy when no
compiler is available — the native path is an optimization of the data
plane, never a requirement (the reference's data plane performance
likewise came from its substrate, Spark; SURVEY.md §2 native census).
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

from distkeras_tpu.utils.locks import TracedLock

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "native")
_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_NATIVE_DIR, "dataloader.cc")
_SO = os.path.join(_PKG_DIR, "_libdkt_data.so")

# Build-cache lock (leaf): held across the one-time g++ build — a
# long first acquire by design, never on a serving/training hot path.
_lock = TracedLock("native.build")
_lib = None
_tried = False

_DEF_THREADS = min(8, os.cpu_count() or 1)


def _compile(src: str, so: str) -> str | None:
    """g++ one source file into a shared library; None on any failure
    (no compiler, bad toolchain) — callers fall back to numpy/python."""
    if not os.path.exists(src):
        return None
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-pthread", src, "-o", so]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError):
        return None
    return so


def _build() -> str | None:
    return _compile(_SRC, _SO)


_BPE_SRC = os.path.join(_NATIVE_DIR, "tokenizer.cc")
_BPE_SO = os.path.join(_PKG_DIR, "_libdkt_bpe.so")
_bpe_lib = None
_bpe_tried = False


def bpe_lib():
    """The BPE tokenizer library (native/tokenizer.cc), or None."""
    global _bpe_lib, _bpe_tried
    with _lock:
        if _bpe_lib is not None or _bpe_tried:
            return _bpe_lib
        _bpe_tried = True
        path = (_BPE_SO if os.path.exists(_BPE_SO)
                else _compile(_BPE_SRC, _BPE_SO))
        if not path:
            return None
        try:
            handle = ctypes.CDLL(path)
        except OSError:
            return None
        handle.dkt_bpe_train.restype = ctypes.c_int32
        handle.dkt_bpe_train.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32, ctypes.c_void_p]
        handle.dkt_bpe_encode.restype = ctypes.c_int64
        handle.dkt_bpe_encode.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_void_p]
        handle.dkt_bpe_decode.restype = ctypes.c_int64
        handle.dkt_bpe_decode.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64]
        _bpe_lib = handle
        return _bpe_lib


def lib():
    """The loaded library, building it if needed; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        path = _SO if os.path.exists(_SO) else _build()
        if not path:
            return None
        try:
            handle = ctypes.CDLL(path)
        except OSError:
            return None
        handle.dkt_gather_f32.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int]
        handle.dkt_gather_bytes.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int]
        handle.dkt_gather_u8_normalize.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_float, ctypes.c_float,
            ctypes.c_int]
        _lib = handle
        return _lib


def available() -> bool:
    return lib() is not None


def _as_c(a: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(a)


def _check_idx(idx: np.ndarray, n_rows: int) -> np.ndarray:
    """Bounds-check (both paths, so numpy fallback matches native: no
    negative-index wrapping) and coerce to contiguous int64."""
    idx = np.ascontiguousarray(idx, dtype=np.int64)
    if idx.size and (idx.min() < 0 or idx.max() >= n_rows):
        raise IndexError(f"gather index out of range for {n_rows} rows")
    return idx


def _check_out(out: np.ndarray, shape: tuple, dtype) -> np.ndarray:
    if out.shape != shape or out.dtype != np.dtype(dtype):
        raise ValueError(
            f"out buffer mismatch: need {shape} {np.dtype(dtype)}, got "
            f"{out.shape} {out.dtype}")
    if not out.flags.c_contiguous:
        raise ValueError("out buffer must be C-contiguous (reshape of a "
                         "non-contiguous buffer would write into a copy)")
    return out


def gather_rows(src: np.ndarray, idx: np.ndarray,
                out: np.ndarray | None = None,
                n_threads: int = _DEF_THREADS) -> np.ndarray:
    """``src[idx]`` for row-major arrays, multithreaded when native.

    Equivalent to numpy fancy indexing on axis 0; the native path runs
    the row memcpys across threads (fancy indexing is single-threaded).
    """
    handle = lib()
    src = _as_c(src)
    idx = _check_idx(idx, len(src))
    out_shape = (len(idx), *src.shape[1:])
    if out is not None:
        out = _check_out(out, out_shape, src.dtype)
    if handle is None:
        result = src[idx]
        if out is not None:
            out[...] = result
            return out
        return result
    if out is None:
        out = np.empty(out_shape, src.dtype)
    if idx.size == 0:
        # the reshape(n, -1)s below raise for size-0 arrays (this also
        # covers an empty src, where len(src) rows can't reshape either)
        return out
    rows = src.reshape(len(src), -1)
    flat_out = out.reshape(len(idx), -1)
    if src.dtype == np.float32:
        handle.dkt_gather_f32(
            rows.ctypes.data, idx.ctypes.data, flat_out.ctypes.data,
            len(idx), rows.shape[1], n_threads)
    else:
        handle.dkt_gather_bytes(
            rows.view(np.uint8).ctypes.data, idx.ctypes.data,
            flat_out.view(np.uint8).ctypes.data,
            len(idx), rows.shape[1] * src.dtype.itemsize, n_threads)
    return out


def gather_normalize_u8(src: np.ndarray, idx: np.ndarray, scale: float,
                        bias: float = 0.0, out: np.ndarray | None = None,
                        n_threads: int = _DEF_THREADS) -> np.ndarray:
    """``src[idx].astype(f32) * scale + bias`` fused (uint8 images)."""
    if src.dtype != np.uint8:
        raise TypeError(f"gather_normalize_u8 needs uint8, got {src.dtype}")
    handle = lib()
    src = _as_c(src)
    idx = _check_idx(idx, len(src))
    out_shape = (len(idx), *src.shape[1:])
    if out is not None:
        out = _check_out(out, out_shape, np.float32)
    if handle is None:
        result = src[idx].astype(np.float32) * scale + bias
        if out is not None:
            out[...] = result
            return out
        return result
    if out is None:
        out = np.empty(out_shape, np.float32)
    if idx.size == 0:
        # reshape(0, -1) below would raise; nothing to copy anyway.
        return out
    handle.dkt_gather_u8_normalize(
        src.reshape(len(src), -1).ctypes.data, idx.ctypes.data,
        out.reshape(len(idx), -1).ctypes.data,
        len(idx), int(np.prod(src.shape[1:])), scale, bias, n_threads)
    return out
