"""Local-replica trainers: AEASGD, EAMSGD, DOWNPOUR, Averaging, Ensemble.

Reference parity: distkeras/trainers.py::AEASGD / EAMSGD / DOWNPOUR /
AveragingTrainer / EnsembleTrainer + the corresponding workers
(distkeras/workers.py) and the DeltaParameterServer that holds the
"center variable" (distkeras/parameter_servers.py).

Unlike ADAG (which maps to plain gradient accumulation), these
algorithms *genuinely maintain divergent per-replica parameters* between
synchronizations — that is their published math (EASGD: Zhang et al.
2015; DOWNPOUR: Dean et al. 2012; see PAPERS.md).  The TPU-native
construction keeps that: each device on the mesh's ``data`` axis holds
its own full parameter/optimizer state (a *stacked* pytree sharded on
the leading replica axis), runs ``communication_window`` local steps
inside a ``lax.scan``, and then executes the algorithm's
synchronization as an explicit collective inside ``shard_map`` —
``psum``/``pmean`` over the ICI where the reference pickled whole
weight vectors through one TCP socket per worker (SURVEY.md §3.2's
scalability bottleneck).

Synchronization rules (SURVEY.md §7.4):
  * AEASGD — elastic: x_i -= a·(x_i − x̃);  x̃ += a·Σ_i(x_i − x̃), a = rho·lr
  * EAMSGD — AEASGD with Nesterov momentum on the local steps
  * DOWNPOUR — commit mean delta and pull: x̃ += mean_i(x_i − x̃); x_i = x̃
  * Averaging — x̃ = mean_i(x_i) once per epoch; x_i = x̃
  * Ensemble — no synchronization at all; k independent models
"""

from __future__ import annotations

import warnings
from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P
from distkeras_tpu.parallel.compat import shard_map

from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.models.adapter import TrainState
from distkeras_tpu.parallel.mesh import equal_across_hosts
from distkeras_tpu.trainers.distributed import DistributedTrainer

# A sync rule: (local_tv, center_tv, axis_name) -> (new_local_tv, new_center_tv)
SyncFn = Callable


def _easgd_sync(alpha: float):
    def sync(tv, center, axis):
        diff = jax.tree.map(lambda x, c: x - c, tv, center)
        new_tv = jax.tree.map(lambda x, d: x - alpha * d, tv, diff)
        new_center = jax.tree.map(
            lambda c, d: c + alpha * jax.lax.psum(d, axis), center, diff)
        return new_tv, new_center
    return sync


def _downpour_sync(tv, center, axis):
    new_center = jax.tree.map(
        lambda c, x: c + jax.lax.pmean(x - c, axis), center, tv)
    return new_center, new_center


def _averaging_sync(tv, center, axis):
    mean = jax.tree.map(lambda x: jax.lax.pmean(x, axis), tv)
    return mean, mean


def _no_sync(tv, center, axis):
    return tv, center


class ReplicaTrainer(DistributedTrainer):
    """Shared machinery: stacked per-replica state + shard_map round.

    One jitted "round" consumes ``[n_replicas, window, batch, ...]`` of
    data: every replica scans its ``window`` microbatches locally, then
    the subclass's sync rule runs as a collective.  The whole round —
    local steps *and* synchronization — is a single XLA program.

    ``device_data=True`` stages each replica's consumption stream in
    its own device's HBM once (P("data") over the replica axis, same
    stream layout as ADAG._fit_device_data_multihost); each round then
    ships only a replicated ``[window * batch]`` index block and the
    round's shard_map gathers locally before the unchanged scan+sync —
    data order is bit-for-bit the streaming path's (parity-tested).
    """

    sync_fn: SyncFn = staticmethod(_no_sync)
    _supports_device_data = True

    def __init__(self, keras_model, loss="categorical_crossentropy", **kw):
        plan = kw.get("plan")
        if kw.pop("fsdp", False) or (
                plan is not None and getattr(plan, "fsdp_axis", None)):
            raise ValueError(
                f"{type(self).__name__} cannot use FSDP: each replica "
                "holds intentionally divergent full weights (that is the "
                "algorithm), so there is no single parameter set to "
                "scatter. Use ADAG/DynSGD with fsdp=True for "
                "memory-sharded data parallelism.")
        if kw.pop("zero1", False) or kw.pop("zero", 0) or (
                plan is not None and (getattr(plan, "zero1", False)
                                      or getattr(plan, "zero", 0))):
            raise ValueError(
                f"{type(self).__name__} cannot use zero1/zero=: each "
                "replica runs its own full optimizer on intentionally "
                "divergent weights (that is the algorithm), so there is "
                "no single update to shard. Use ADAG/DynSGD with zero= "
                "for the sharded stages.")
        super().__init__(keras_model, loss=loss, **kw)

    # ------------------------------------------------------------ state

    def _stack_state(self, states: list[TrainState]) -> TrainState:
        """Stack k host-side TrainStates into one [k, ...] pytree."""
        return jax.tree.map(lambda *xs: jnp.stack(xs), *states)

    def _n_local(self) -> int:
        """Replicas this process owns (all of them single-process)."""
        return self.num_workers // jax.process_count()

    def _replica_states(self) -> TrainState:
        """The *local* replica stack ``[n_local, ...]``; single-process
        that is the whole thing, multi-process each host builds only its
        slice (assembled into the global array by :meth:`_put`)."""
        base = self.adapter.init_state()
        return jax.tree.map(
            lambda a: jnp.broadcast_to(a[None], (self._n_local(),) + a.shape),
            base)

    def _put(self, stacked: TrainState, center_tv):
        repl_sh = NamedSharding(self.mesh, P("data"))
        rep = NamedSharding(self.mesh, P())
        if jax.process_count() == 1:
            stacked = jax.tree.map(
                lambda a: jax.device_put(a, repl_sh), stacked)
            return stacked, jax.device_put(center_tv, rep)
        # Multi-process: each host contributes its local replicas' slab;
        # the global [n, ...] array spans all hosts' devices.  The
        # center variable is replicated from identical local copies.
        n = self.num_workers
        stacked = jax.tree.map(
            lambda a: jax.make_array_from_process_local_data(
                repl_sh, np.asarray(a), (n,) + tuple(a.shape[1:])), stacked)
        center_tv = jax.tree.map(
            lambda a: jax.make_array_from_process_local_data(
                rep, np.asarray(a), tuple(a.shape)), center_tv)
        return stacked, center_tv

    def _eval_state_view(self, pytree):
        if isinstance(pytree, dict):  # mid-fit round pytree
            # Evaluate the center variable (the algorithm's product);
            # aux state (BatchNorm stats) from replica 0.  The slice is
            # compiled with replicated output, same as the export path:
            # an eager a[0] cannot read non-addressable shards in the
            # multi-process runtime (and all hosts reach here in
            # lockstep, so the collective is safe).
            if getattr(self, "_eval_slice0", None) is None:
                self._eval_slice0 = jax.jit(
                    lambda s: jax.tree.map(lambda a: a[0], s),
                    out_shardings=NamedSharding(self.mesh, P()))
            return pytree["center_tv"], self._eval_slice0(
                pytree["stacked"].ntv)
        return super()._eval_state_view(pytree)

    # ------------------------------------------------------------ round

    def _make_round(self, window: int, indexed: bool = False):
        train_step = self.adapter.make_train_step()
        sync_fn = self.sync_fn
        mesh = self.mesh
        B = self.batch_size

        def scan_and_sync(stacked, center_tv, xs, ys):
            # Per-device views: stacked leaves [1, ...], xs [w, B, ...].
            local = jax.tree.map(lambda a: a[0], stacked)

            def micro(st, batch):
                x, y = batch
                st2, loss = train_step(st, x, y)
                return st2, loss

            local, losses = jax.lax.scan(micro, local, (xs, ys))
            new_tv, new_center = sync_fn(local.tv, center_tv, "data")
            local = local.replace(tv=new_tv)
            mean_loss = jax.lax.pmean(jnp.mean(losses), "data")
            return (jax.tree.map(lambda a: a[None], local), new_center,
                    mean_loss)

        def local_round(stacked, center_tv, xs, ys):
            return scan_and_sync(stacked, center_tv, xs[0], ys[0])

        def local_round_indexed(stacked, center_tv, Xb, Yb, idx):
            # Xb is THIS replica's staged consumption stream; idx is the
            # replicated block-local offset vector (identical per
            # replica), so the gather is purely device-local.
            shape = lambda a: (window, B) + a.shape[1:]
            xs = jnp.take(Xb, idx, axis=0).reshape(shape(Xb))
            ys = jnp.take(Yb, idx, axis=0).reshape(shape(Yb))
            return scan_and_sync(stacked, center_tv, xs, ys)

        data_specs = ((P("data"), P("data"), P())
                      if indexed else (P("data"), P("data")))
        sharded = shard_map(
            local_round_indexed if indexed else local_round, mesh=mesh,
            in_specs=(P("data"), P()) + data_specs,
            out_specs=(P("data"), P(), P()),
            check_vma=False,
        )
        return jax.jit(sharded, donate_argnums=(0, 1))

    # ------------------------------------------------------------ fit

    def _round_stream(self, dataset: Dataset, window: int):
        """Yield this host's [n_local, w, B, ...] stacks per epoch.

        Single-process that is the full [n, w, B, ...] round; in the
        multi-process runtime each host streams its ``Dataset.shard``
        to its local replicas (replica ``h * n_local + i`` trains on
        host h's i-th slab — document/construct shards accordingly when
        exact replica assignment matters).
        """
        n = self._n_local()
        for _ in range(self.num_epoch):
            for xs, ys in dataset.batches(
                    self.batch_size, features_col=self.features_col,
                    label_col=self.label_col, window=n * window):
                # [n*w, B, ...] -> [n, w, B, ...]
                yield (xs.reshape((n, window) + xs.shape[1:]),
                       ys.reshape((n, window) + ys.shape[1:]))

    def _index_rounds(self, dataset: Dataset, window: int):
        """Device-resident analogue of :meth:`_round_stream`: stage each
        replica's consumption stream in HBM once (stream layout: host
        rows ``[rounds, n_local, w*B, ...]`` transposed to
        ``[n_local, rounds*w*B, ...]``, sharded P("data") so device i's
        contiguous shard is replica i's stream), then yield one
        ``(X, Y, idx)`` per round where idx is a replicated block-local
        offset vector — the rows streaming would feed, in order."""
        n_local = self._n_local()
        rows = n_local * window * self.batch_size
        usable = len(dataset) - len(dataset) % rows
        rounds = usable // rows
        wb = window * self.batch_size

        def layout(col):
            a = np.asarray(col[:usable])
            a = a.reshape((rounds, n_local, wb) + a.shape[1:])
            a = np.moveaxis(a, 1, 0)
            return np.ascontiguousarray(a.reshape((usable,) + a.shape[3:]))

        sh = NamedSharding(self.mesh, P("data"))
        rep = NamedSharding(self.mesh, P())
        X = self._global_batch(layout(dataset[self.features_col]), sh)
        Y = self._global_batch(layout(dataset[self.label_col]), sh)
        multi = jax.process_count() > 1
        for _ in range(self.num_epoch):
            for r in range(rounds):
                idx = np.arange(r * wb, (r + 1) * wb, dtype=np.int32)
                # Replicated blocks need the explicit global shape
                # (every host holds the identical copy; _global_batch
                # would concatenate hosts' rows) — same idiom as
                # ADAG._fit_device_data_multihost's index blocks.
                yield (X, Y,
                       jax.make_array_from_process_local_data(
                           rep, idx, idx.shape) if multi
                       else jax.device_put(idx, rep))

    def _window(self, dataset: Dataset) -> int:
        return self.communication_window

    def _fit(self, dataset: Dataset):
        pcount = jax.process_count()
        if pcount > 1 and self.num_workers % pcount:
            raise ValueError(
                f"num_workers={self.num_workers} must divide by the "
                f"process count ({pcount}): each host owns an equal "
                "share of the replica stack")
        window = self._window(dataset)
        stacked = self._replica_states()
        center_tv = self.adapter.init_state().tv
        stacked, center_tv = self._put(stacked, center_tv)
        round_fn = self._make_round(window, indexed=self.device_data)
        batch_sh = NamedSharding(self.mesh, P("data"))

        def globalize(a):
            if pcount == 1:
                return a
            return jax.make_array_from_process_local_data(
                batch_sh, a, (self.num_workers,) + tuple(a.shape[1:]))

        # Lockstep safety: unequal round counts deadlock the sync
        # collective (one shared definition — mesh.equal_across_hosts).
        rows = self.batch_size * self._n_local() * window
        equal_across_hosts((len(dataset) // rows) * self.num_epoch,
                           f"round counts ({rows}-row windows)")

        restored, start = self._restore_or(
            {"stacked": stacked, "center_tv": center_tv})
        stacked, center_tv = restored["stacked"], restored["center_tv"]
        if self.device_data:
            rounds_iter = self._index_rounds(dataset, window)
        else:
            rounds_iter = ((globalize(xs), globalize(ys))
                           for xs, ys in self._round_stream(dataset, window))
        losses, rnd = [], 0
        for args in rounds_iter:
            rnd += 1
            if rnd <= start:
                continue
            stacked, center_tv, loss = round_fn(
                stacked, center_tv, *args)
            losses.append(loss)
            self._checkpoint({"stacked": stacked, "center_tv": center_tv}, rnd)
            self._eval_hook({"stacked": stacked, "center_tv": center_tv}, rnd)
        if losses or not start:  # resumed-past-the-end runs skip straight to export
            self._require_steps(
                losses, self.batch_size * self._n_local() * window,
                len(dataset))
            self._record(losses)
            self._checkpoint({"stacked": stacked, "center_tv": center_tv},
                             rnd, final=True)
        self._final_stacked = stacked  # kept for ensemble export
        # Export the center variable; aux state (BatchNorm stats etc.)
        # taken from replica 0.  The slice is compiled with replicated
        # output so every host can materialize it (an eager a[0] cannot
        # read non-addressable shards in the multi-process runtime).
        first = jax.jit(lambda s: jax.tree.map(lambda a: a[0], s),
                        out_shardings=NamedSharding(self.mesh, P()))(stacked)
        return first.replace(tv=center_tv)


class AEASGD(ReplicaTrainer):
    """Asynchronous Elastic Averaging SGD, synchronous-elastic form.

    Reference parity: distkeras/trainers.py::AEASGD (rho,
    communication_window, learning_rate).  The elastic coefficient is
    a = rho * learning_rate, as in the reference workers' elastic force.
    """

    def __init__(self, keras_model, communication_window: int = 32,
                 rho: float = 5.0, learning_rate: float = 0.01, **kw):
        if callable(learning_rate):
            raise ValueError(
                "AEASGD/EAMSGD need a scalar learning_rate: the elastic "
                "coefficient alpha = rho * learning_rate is part of the "
                "algorithm's fixed-point math (reference elastic force), "
                "not just an optimizer step size, so an optax schedule "
                "has no single value to derive it from. Use a scalar "
                "here, or ADAG/DOWNPOUR/SingleTrainer for scheduled LR.")
        super().__init__(keras_model, learning_rate=learning_rate, **kw)
        self.communication_window = communication_window
        self.rho = rho
        alpha = rho * learning_rate
        n = self.num_workers
        if alpha * n >= 1.0:
            # Keep the center update contractive; the reference's async
            # form hides this with staleness, the sync form must not blow up.
            clamped = 0.9 / n
            warnings.warn(
                f"AEASGD elastic coefficient rho*learning_rate = {alpha:g} "
                f"violates the synchronous stability bound "
                f"rho*learning_rate*num_workers < 1 (num_workers={n}); "
                f"clamping to {clamped:g}. Lower rho or learning_rate to "
                "run the requested coefficient (see docs/algorithms.md).",
                stacklevel=2)
            alpha = clamped
        self.alpha = alpha
        self.sync_fn = _easgd_sync(alpha)


class EAMSGD(AEASGD):
    """Elastic Averaging Momentum SGD.

    Reference parity: distkeras/trainers.py::EAMSGD — AEASGD plus
    Nesterov momentum on the local worker updates (SURVEY.md §3.3).
    """

    def __init__(self, keras_model, communication_window: int = 32,
                 rho: float = 5.0, learning_rate: float = 0.01,
                 momentum: float = 0.9, **kw):
        import optax

        kw.setdefault("worker_optimizer",
                      optax.sgd(learning_rate, momentum=momentum,
                                nesterov=True))
        super().__init__(keras_model,
                         communication_window=communication_window,
                         rho=rho, learning_rate=learning_rate, **kw)
        self.momentum = momentum


class DOWNPOUR(ReplicaTrainer):
    """DOWNPOUR SGD, synchronous form.

    Reference parity: distkeras/trainers.py::DOWNPOUR — workers
    accumulate local updates for ``communication_window`` batches, then
    commit the delta and pull the center (SURVEY.md §3.3).  Synchronous
    semantics: all replicas commit at once, the center advances by the
    *mean* delta, and replicas restart from the new center; per-replica
    optimizer state (the reference's worker-local Adagrad etc.) persists
    across windows.
    """

    sync_fn = staticmethod(_downpour_sync)

    def __init__(self, keras_model, communication_window: int = 5, **kw):
        kw.setdefault("worker_optimizer", "adagrad")
        super().__init__(keras_model, **kw)
        self.communication_window = communication_window


class AveragingTrainer(ReplicaTrainer):
    """Model averaging: independent epoch training, then weight mean.

    Reference parity: distkeras/trainers.py::AveragingTrainer (workers
    train on their partition; the driver averages all resulting weight
    sets).  Here the average is a ``pmean`` once per epoch.
    """

    sync_fn = staticmethod(_averaging_sync)

    def __init__(self, keras_model, **kw):
        super().__init__(keras_model, **kw)

    def _window(self, dataset: Dataset) -> int:
        # One sync per epoch: window = batches each replica owns per epoch.
        w = len(dataset) // (self.batch_size * self.num_workers)
        if w < 1:
            raise ValueError("dataset too small for one batch per replica")
        return w


class EnsembleTrainer(ReplicaTrainer):
    """Train k independent models in parallel; return all of them.

    Reference parity: distkeras/trainers.py::EnsembleTrainer
    (num_models).  Each replica slot trains its own independently
    initialized model on its own data stream; there is no collective in
    the round at all.  ``train()`` returns a *list* of Keras models.
    """

    sync_fn = staticmethod(_no_sync)

    def __init__(self, keras_model, num_models: int | None = None, **kw):
        window = kw.pop("communication_window", 8)
        if kw.get("eval_every"):
            raise ValueError(
                "EnsembleTrainer has no single model to evaluate "
                "mid-training (its members are intentionally "
                "independent); evaluate the returned models with "
                "ModelPredictor + AccuracyEvaluator instead")
        if num_models is not None:
            kw.setdefault("num_workers", num_models)
        super().__init__(keras_model, **kw)
        self.num_models = self.num_workers
        self.communication_window = window

    def train(self, dataset, features_col=None, label_col=None,
              eval_dataset=None):
        if eval_dataset is not None:
            raise ValueError(
                "EnsembleTrainer returns k independent models; evaluate "
                "them individually (ModelPredictor + AccuracyEvaluator) "
                "rather than through eval_dataset")
        return super().train(dataset, features_col=features_col,
                             label_col=label_col)

    def _replica_states(self) -> TrainState:
        # Independent initializations per member, derived from the
        # trainer seed for reproducibility.  Seeds are keyed on the
        # *global* member index, so a multi-process run initializes the
        # same ensemble as a single-process one.
        states = []
        original = self.adapter.model.get_weights()
        host = jax.process_index()
        nl = self._n_local()
        for i in range(host * nl, (host + 1) * nl):
            seed = None if self.seed is None else self.seed + i
            self.adapter.model.set_weights(_reinit_weights(original, seed))
            states.append(self.adapter.init_state())
        self.adapter.model.set_weights(original)
        return self._stack_state(states)

    def _export(self, state) -> list:
        # Single-process: every shard is addressable, slice eagerly
        # (holds one member at a time).  Multi-process: replicate the
        # stack once (compiled all-gather) so every host can
        # materialize every member — the per-device cost is the price
        # of returning all k models on all hosts.
        full = self._final_stacked
        if jax.process_count() > 1:
            full = jax.jit(lambda s: s,
                           out_shardings=NamedSharding(self.mesh, P()))(full)
        models = []
        for i in range(self.num_workers):
            st = jax.tree.map(lambda a: a[i], full)
            models.append(self.adapter.export_model(st))
        return models


def _reinit_weights(weights, seed=None):
    """Fresh glorot-ish reinitialization for matrices; 1-D weights
    (biases, BatchNorm gamma/beta, ...) keep their original init — zeroing
    them would kill normalization layers (gamma must stay at ones)."""
    rng = np.random.default_rng(seed)
    out = []
    for w in weights:
        if w.ndim >= 2:
            fan_in, fan_out = w.shape[-2], w.shape[-1]
            limit = np.sqrt(6.0 / (fan_in + fan_out))
            out.append(rng.uniform(-limit, limit, w.shape).astype(w.dtype))
        else:
            out.append(np.array(w, copy=True))
    return out
