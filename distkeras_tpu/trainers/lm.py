"""LMTrainer: the transformer flagship under the trainer-family API.

The reference's trainer family stops at Keras Sequential models fed by
`train_on_batch` (reference: distkeras/trainers.py); the TPU rebuild's
flagship is the functional transformer (models/transformer.py), and
this class gives it the same user contract as every other trainer —
``LMTrainer(cfg, ...).train(dataset) -> params`` with ``history`` and
``training_time`` — while exposing the full parallelism surface through
two knobs:

- ``mesh``: any MeshSpec mesh; the ``data`` axis shards the batch, a
  ``model`` axis applies Megatron TP (transformer.tp_rules), a ``seq``
  axis switches attention to the ring implementation, an ``expert``
  axis shards MoE experts, and a ``pipeline`` axis pipelines the trunk.
- ``microbatches``: GPipe depth when the mesh has a pipeline axis.

Dataset contract: one column of token rows ``[N, seq_len + 1]`` (inputs
plus the shifted targets, as lm_loss expects).
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from distkeras_tpu.parallel.compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from distkeras_tpu import obs
from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.models import transformer as tfm
from distkeras_tpu.parallel.mesh import (AXES, make_mesh,
                                          global_batch as mesh_global_batch)
from distkeras_tpu.parallel.ring import make_ring_attention
from distkeras_tpu.parallel.sharding import ShardingPlan
from distkeras_tpu.trainers.base import CheckpointingBase
from distkeras_tpu.utils.profiling import StepTimer


_OPTS = {
    "adam": optax.adam,
    "adamw": optax.adamw,
    "sgd": optax.sgd,
}

# Staging more than this fraction of reported device memory fails fast
# (the rest of the step still needs activations/params/moments).
_STAGING_FRACTION = 0.8
# With no backend memory report (CPU), only an absurd estimate warns.
_STAGING_SANITY_BYTES = 8 << 30


def _device_bytes_limit():
    """Per-device memory budget in bytes, or None when the backend
    does not report one (CPU test meshes).  Module-level so tests can
    monkeypatch a tiny budget to exercise the staging guard."""
    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return None
    return (stats or {}).get("bytes_limit")


def _with_ema(opt, decay: float):
    """Wrap an optax transform so its state carries a Polyak/EMA shadow
    of the parameters: ``state = (inner_state, ema_params)``.

    The shadow updates with the POST-step parameters each optimizer
    step (``apply_updates`` on the incoming params — the same value the
    train step is about to adopt).  Living inside the optimizer state
    means checkpoint/resume and the params-like positional sharding
    rule (_state_shardings) cover it for free; LMTrainer exposes it as
    ``.ema_params`` after training.
    """
    def init(params):
        return opt.init(params), jax.tree.map(jnp.asarray, params)

    def update(grads, state, params=None, **kw):
        inner, shadow = state
        updates, inner = opt.update(grads, inner, params, **kw)
        stepped = optax.apply_updates(params, updates)
        shadow = jax.tree.map(
            lambda s, q: decay * s + (1.0 - decay) * q, shadow, stepped)
        return updates, (inner, shadow)

    return optax.GradientTransformation(init, update)


def _make_zero_step(cfg: tfm.TransformerConfig, inner, mesh, layout,
                    stage: int, grad_accum: int, probe: bool):
    """The ZeRO stage-2/3 train step for the pure-DP LM
    (docs/zero1.md): the gradient accumulator is the SCATTERED fusion-
    bucket layout — each microbatch's bucketed reduce-scatter
    interleaves into the accumulation loop (``collectives.scatter`` on
    the carry), so a replica only ever materializes its 1/n gradient
    shard — and the update runs on the shard views via ``inner`` (the
    raw optax chain, whose state the trainer inits over views).

    Stage 2 keeps ``params`` replicated and all-gathers the update;
    stage 3 takes ``params`` AS the ``[n, cols]`` shard-view tree,
    re-materializes full parameters per fusion bucket just-in-time
    inside the loss (``collectives.gather_bucket``: all-gather forward,
    reduce-scatter backward) and returns the updated views — no
    parameter all-gather leg at all.
    """
    from distkeras_tpu.parallel.collectives import (all_gather,
                                                    gather_bucket,
                                                    scatter)

    dropping = cfg.dropout > 0
    scope = "zero3/grad_accum" if stage >= 3 else "zero2/accum_scatter"

    def loss_of_views(v, tok, rng, seg):
        buckets = [gather_bucket(b, mesh) for b in layout.pack_views(v)]
        full = layout.unpack(buckets)
        return tfm.lm_loss(full, tok, cfg, None, None, rng, None, seg)

    def loss_full(p, tok, rng, seg):
        return tfm.lm_loss(p, tok, cfg, None, None, rng, None, seg)

    def step(carry, tokens, dropout_rng=None, segment_ids=None):
        params, opt_state = carry
        if dropping and dropout_rng is None:
            raise ValueError(
                f"cfg.dropout={cfg.dropout} but the train step got no "
                "dropout_rng (LMTrainer threads the rng automatically)")
        rng = dropout_rng if dropping else None
        grad_fn = jax.value_and_grad(
            loss_of_views if stage >= 3 else loss_full)
        acc = layout.zero_buckets()
        loss = jnp.zeros((), jnp.float32)
        for i in range(grad_accum):
            tok = tokens[i] if grad_accum > 1 else tokens
            seg = (None if segment_ids is None
                   else segment_ids[i] if grad_accum > 1
                   else segment_ids)
            ri = (jax.random.fold_in(rng, i)
                  if rng is not None and grad_accum > 1 else rng)
            li, gi = grad_fn(params, tok, ri, seg)
            g_bks = (layout.pack_views(gi) if stage >= 3
                     else layout.pack(gi))
            with jax.named_scope(scope):
                acc = [scatter(a + b, mesh) for a, b in zip(acc, g_bks)]
            loss = loss + li
        g_views = layout.views_from_buckets(
            [b / grad_accum for b in acc])
        p_views = params if stage >= 3 else layout.shard_views(params)
        with jax.named_scope(f"zero{stage}/update"):
            u_views, opt_state = inner.update(g_views, opt_state,
                                              p_views)
        if stage >= 3:
            params = jax.tree.map(lambda p, u: p + u, params, u_views)
        else:
            with jax.named_scope("zero2/all_gather"):
                u_buckets = [all_gather(b, mesh)
                             for b in layout.pack_views(u_views)]
            params = jax.tree.map(lambda p, u: p + u, params,
                                  layout.unpack(u_buckets))
        loss = loss / grad_accum
        if probe:
            return (params, opt_state), (
                loss, {"grad_norm": optax.global_norm(g_views)})
        return (params, opt_state), loss

    return step


def _make_localsgd_step(cfg: tfm.TransformerConfig, optimizer, mesh,
                        config):
    """Local-SGD train step for the pure-DP LM (docs/lowcomm.md):
    ``step((params, opt), tokens[H, B, S+1])`` runs, per replica inside
    a shard_map over ``data``, ``H = config.sync_every`` purely-local
    optimizer steps on this replica's batch shards, then ONE
    cross-replica merge — parameter deltas by the configured rule
    (mean / adasum per fusion bucket) and floating optimizer-state
    leaves averaged (momentum-aware).  1/H the collective frequency of
    the synchronous step; pinned by the collective census."""
    from distkeras_tpu.parallel.exchange import (merge_local_params,
                                                 sync_local_tree)

    def step(carry, tokens, dropout_rng=None, segment_ids=None):
        if dropout_rng is not None or segment_ids is not None:
            raise ValueError(
                "sync_every > 1 does not support dropout or packed "
                "segments (replica-local loss)")
        params, opt_state = carry
        n_data = int(mesh.shape["data"])

        def local_run(params, opt_state, tokens):
            grad_fn = jax.value_and_grad(tfm.lm_loss)

            def local_step(c, tok):
                p, s = c
                loss, g = grad_fn(p, tok, cfg, None, None, None, None,
                                  None)
                u, s = optimizer.update(g, s, p)
                p = jax.tree.map(lambda a, b: a + b, p, u)
                return (p, s), loss

            (p, s), losses = jax.lax.scan(
                local_step, (params, opt_state), tokens)
            with jax.named_scope("exchange/localsgd_sync"):
                p = merge_local_params(params, p, config, "data", n_data)
                s = sync_local_tree(s, config, "data", n_data)
                loss = jax.lax.pmean(jnp.mean(losses), "data")
            return (p, s), loss

        return shard_map(local_run, mesh=mesh,
                         in_specs=(P(), P(), P(None, "data", None)),
                         out_specs=((P(), P()), P()),
                         check_vma=False)(params, opt_state, tokens)

    return step


class LMTrainer(CheckpointingBase):
    """Train a causal transformer LM over a device mesh.

    Carries the full trainer-family contract: ``history`` /
    ``training_time``, ``shuffle`` (+ ``seed``), and orbax
    checkpoint/resume through ``checkpoint_dir`` / ``checkpoint_every``
    / ``max_checkpoints`` / ``resume`` — the same knobs as
    :class:`~distkeras_tpu.trainers.base.Trainer` (reference keeps one
    uniform contract across its family, distkeras/trainers.py).
    A checkpoint round is one optimizer step.

    ``device_data=True`` stages the token rows in HBM ONCE (int32 —
    cheap relative to activations), sharded over the ``data`` axis in
    consumption-stream layout; each step then ships only a tiny
    replicated index block and gathers its batch on device
    (_stage_stream).  This is the distributed/flagship form of the
    input-pipeline win measured in docs/perf_input_pipeline.md (the
    host link caps streaming); composes with fsdp/TP/ring/pipeline
    meshes and grad_accum/segments because the gather feeds the
    unchanged train step inside the same jitted program.  Data order
    is bit-for-bit the streaming path's (parity-tested).

    ``zero=1|2|3``: ZeRO sharding stages (docs/zero1.md; identical
    training math, pure-DP meshes only, ~``zero_bucket_mb`` fusion
    buckets).  Stage 1 (alias ``zero1=True``) shards the weight
    update: reduce-scatter(grads) -> each replica updates its shard ->
    all-gather(update); optimizer memory (adam moments, the EMA
    shadow) and update FLOPs drop ~data-axis x at unchanged comm
    volume.  Stage 2 additionally shards the gradient accumulator —
    each microbatch's bucketed reduce-scatter interleaves into the
    ``grad_accum`` loop, so a replica only materializes its 1/n
    gradient shard.  Stage 3 additionally holds the PARAMETERS as
    chunk-major ``[n, cols]`` shard views with bucket-granular
    gather-on-use (collectives.gather_bucket) and updates the views in
    place — per-device param+grad+opt bytes all drop ~data-axis x.
    ``fsdp=True`` is the GSPMD dimension-sharded ZeRO-3 alternative
    when TP composition matters.

    **Gradient-exchange policy** (docs/lowcomm.md; pure-DP meshes, no
    dropout/MoE/segments): ``merge_rule="adasum"`` merges replica
    gradients by pairwise adaptive summation instead of the mean
    (arXiv 2006.02924); ``sync_every=H`` switches to local-SGD — H
    purely-local optimizer steps then one momentum-aware parameter
    merge, 1/H the collective frequency (the WAN-tolerant mode for the
    cluster substrate); ``compress="int8"``/``"topk"`` applies an
    error-feedback codec per fusion bucket (~4x fewer gradient wire
    bytes for int8, pinned by the collective census).
    ``compress="int8"`` composes with ``zero1=True`` by compressing
    the reduce-scatter leg.  ``probe_metrics=True`` adds an in-graph
    grad-norm probe (``probe_history``; zero extra compiled programs).

    ``ema_decay``: maintain a Polyak/EMA average of the weights inside
    the optimizer state (decay per optimizer step); after ``train``,
    ``self.ema_params`` holds the servable averaged tree.  Composes
    with the mesh/checkpoint/accum features because the shadow is just
    more optimizer state.  Not offered on LoRATrainer (its optax.masked
    re-wrap would shadow a MaskedNode-laden packed tree; the servable
    artifact there is the merged tree ``train`` already returns).
    """

    @property
    def ema_params(self):
        """EMA weight tree from the last ``train`` call (requires
        ``ema_decay``); None before training."""
        if not self._ema:
            raise ValueError("ema_params requires ema_decay= on the "
                             "constructor")
        return self._ema_params

    def __init__(self, cfg: tfm.TransformerConfig, optimizer="adamw",
                 learning_rate: float = 3e-4, weight_decay: float | None = None,
                 batch_size: int = 8,
                 num_epoch: int = 1, mesh=None, rules=None,
                 microbatches: int | None = None, fsdp: bool = False,
                 zero: int | None = None,
                 zero1: bool = False, zero1_bucket_mb: float | None = None,
                 zero_bucket_mb: float | None = None,
                 device_data: bool = False,
                 grad_accum: int = 1, grad_clip_norm: float | None = None,
                 merge_rule: str = "mean", sync_every: int = 1,
                 compress=None, topk_frac: float = 0.01,
                 probe_metrics: bool = False,
                 tokens_col: str = "tokens", seed: int = 0,
                 shuffle: bool = False, eval_every: int = 0,
                 profile_dir: str | None = None, profile_steps: int = 3,
                 checkpoint_dir: str | None = None, checkpoint_every: int = 0,
                 max_checkpoints: int = 3, resume: bool = False,
                 checkpoint_backend: str = "auto",
                 ema_decay: float | None = None):
        self.cfg = cfg
        from distkeras_tpu.trainers.base import normalize_zero_args

        zero, zero1, zero_bucket_mb = normalize_zero_args(
            zero, zero1, zero_bucket_mb, zero1_bucket_mb)
        if not callable(learning_rate) and learning_rate <= 0:
            raise ValueError(
                f"learning_rate must be positive, got {learning_rate}")
        if weight_decay is not None and optimizer != "adamw":
            raise ValueError(
                "weight_decay only applies to optimizer='adamw' (pass a "
                "prebuilt optax transform for anything more exotic); "
                f"got optimizer={optimizer!r}")
        if hasattr(optimizer, "init"):  # prebuilt optax GradientTransformation
            self.optimizer = optimizer
        elif callable(optimizer):  # optax factory: optax.lion etc.
            self.optimizer = optimizer(learning_rate)
        elif optimizer == "adamw" and weight_decay is not None:
            # Standard masking: RMSNorm scales are excluded from decay
            # (decaying a normalization gain toward 0 fights the
            # parameterization, not overfitting).
            def decay_mask(params):
                from distkeras_tpu.parallel.compat import keystr

                def leaf(path, _):
                    name = keystr(path, simple=True, separator="/")
                    return not name.endswith("_scale")
                return jax.tree_util.tree_map_with_path(leaf, params)

            self.optimizer = optax.adamw(
                learning_rate, weight_decay=weight_decay, mask=decay_mask)
        else:
            try:
                self.optimizer = _OPTS[optimizer](learning_rate)
            except KeyError:
                raise ValueError(
                    f"unknown optimizer {optimizer!r}; known: {sorted(_OPTS)} "
                    "(or pass an optax factory / GradientTransformation)")
        if grad_clip_norm is not None:
            if grad_clip_norm <= 0:
                raise ValueError(
                    f"grad_clip_norm must be positive, got {grad_clip_norm}")
            self.optimizer = optax.chain(
                optax.clip_by_global_norm(grad_clip_norm), self.optimizer)
        if ema_decay is not None:
            if not 0.0 < ema_decay < 1.0:
                raise ValueError(
                    f"ema_decay must be in (0, 1), got {ema_decay}")
            # The shadow rides INSIDE the optimizer state, so
            # checkpointing, resume, and the params-like sharding rule
            # all cover it with zero extra machinery.
            self.optimizer = _with_ema(self.optimizer, ema_decay)
        self._ema = ema_decay is not None
        self._ema_params = None
        if grad_accum < 1:
            raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")
        self.grad_accum = grad_accum
        if eval_every < 0:
            raise ValueError(f"eval_every must be >= 0, got {eval_every}")
        # Optional XLA profile of a few steady-state steps (skips round
        # 1, which is compile): utils/profiling.trace around rounds
        # [2, 2 + profile_steps); view in TensorBoard/Perfetto.
        if profile_steps < 1:
            raise ValueError(
                f"profile_steps must be >= 1, got {profile_steps}")
        self.profile_dir = profile_dir
        self.profile_steps = profile_steps
        self.batch_size = batch_size
        self.num_epoch = num_epoch
        self.mesh = mesh if mesh is not None else make_mesh()
        self.fsdp = fsdp
        self.device_data = device_data
        self.plan = ShardingPlan(
            rules=tfm.tp_rules() if rules is None else rules,
            fsdp_axis="data" if fsdp else None)
        self.tokens_col = tokens_col
        self.seed = seed
        self.shuffle = shuffle
        self.history: list[float] = []
        self.eval_every = eval_every
        # [(round, {"loss", "perplexity"})]; loss here is pure NLL (no
        # MoE aux), so exp(loss) is honest perplexity.
        self.eval_history: list[tuple[int, dict]] = []
        self.training_time: float = 0.0
        # Same phase observability as the Keras trainer family: "h2d"
        # = host staging + transfer dispatch, "step" = jitted dispatch.
        self.step_timer = StepTimer()
        self._setup_checkpointing(
            checkpoint_dir=checkpoint_dir, checkpoint_every=checkpoint_every,
            max_checkpoints=max_checkpoints, resume=resume, shuffle=shuffle,
            seed=seed, backend=checkpoint_backend)

        missing = [a for a in AXES if a not in self.mesh.shape]
        if missing:
            raise ValueError(
                f"mesh is missing axes {missing}: LMTrainer needs the "
                f"canonical axis set {AXES} (build the mesh with "
                "parallel.mesh.make_mesh / MeshSpec, which always carries "
                "all five, sized 1 when unused)")
        n_pipe = int(self.mesh.shape["pipeline"])
        n_seq = int(self.mesh.shape["seq"])
        n_model = int(self.mesh.shape["model"])
        if (n_model > 1 and rules is None and cfg.n_kv_heads is not None
                and cfg.kv_heads % n_model):
            raise ValueError(
                f"GQA with Megatron TP: n_kv_heads={cfg.kv_heads} must "
                f"divide by the mesh model axis ({n_model}) — the default "
                "tp_rules shard K/V projections over their head "
                "dimension. Use more KV heads, a smaller model axis, or "
                "custom rules.")
        if cfg.dropout > 0 and n_pipe > 1:
            raise ValueError(
                "cfg.dropout > 0 cannot compose with a pipeline axis > 1: "
                "the pipeline's tick schedule is compiled without a "
                "per-microbatch rng stream (TransformerConfig.dropout). "
                "Train with dropout on a dp/tp/sp/fsdp mesh, or drop the "
                "regularizer under PP.")
        if fsdp and n_pipe > 1:
            raise ValueError(
                "fsdp=True cannot compose with a pipeline axis > 1: the "
                "pipelined trunk runs in a manual shard_map over "
                "{pipeline, seq} whose in_specs take the stage-stacked "
                "parameters whole. Shard memory across pipeline stages "
                "instead (that is what PP does), or drop the pipeline axis.")
        if microbatches is not None and n_pipe <= 1:
            raise ValueError(
                "microbatches only applies with a pipeline mesh axis > 1 "
                f"(mesh has pipeline={n_pipe})")
        self.microbatches = microbatches or (2 * n_pipe if n_pipe > 1 else 1)

        self.zero = zero
        self.zero1 = zero1
        self._zero_inner = None
        self._zero_layout_cache = None
        if zero_bucket_mb is not None and not zero:
            raise ValueError(
                "zero_bucket_mb/zero1_bucket_mb only apply with a "
                "ZeRO stage (zero=/zero1=True)")
        from distkeras_tpu.parallel.exchange import ExchangeConfig

        exchange = ExchangeConfig(
            merge_rule=merge_rule, sync_every=sync_every,
            compress=compress, topk_frac=topk_frac,
            # Under zero1 x int8 the exchange's bucket layout IS the
            # zero1 layout, so the one bucket knob governs both.
            **({} if zero_bucket_mb is None
               else {"bucket_mb": zero_bucket_mb}))
        self.exchange = exchange
        self.probe_metrics = probe_metrics
        self.probe_history: list[dict] = []
        if not exchange.is_default:
            pure_dp = (n_model == 1 and n_seq == 1 and n_pipe == 1
                       and int(self.mesh.shape["expert"]) == 1
                       and not fsdp and not cfg.num_experts)
            if not pure_dp:
                raise ValueError(
                    "merge_rule/sync_every/compress compose with the "
                    "pure data-parallel mesh only (no model/seq/"
                    "pipeline/expert axes, no fsdp, no MoE): the "
                    "exchange layer computes per-replica gradients in "
                    "a shard_map over the data axis")
            if cfg.dropout > 0:
                raise ValueError(
                    "merge_rule/sync_every/compress do not compose "
                    "with cfg.dropout > 0: the dropout mask stream is "
                    "a global-batch quantity a replica-local loss "
                    "would draw differently")
            if device_data:
                raise ValueError(
                    "merge_rule/sync_every/compress do not compose "
                    "with device_data=True: the staged data plane "
                    "does not route through the local-gradient "
                    "shard_map")
            if zero and not (zero == 1 and exchange.compress == "int8"
                             and exchange.sync_every == 1):
                raise ValueError(
                    "the ZeRO stages compose with zero=1 + "
                    "compress='int8' only (the chunked codec compresses "
                    "the reduce-scatter leg); adasum, local-SGD, codec "
                    "rules and stages 2/3 replace the exchange the "
                    "sharded update rides")
            if exchange.sync_every > 1 and grad_accum > 1:
                raise ValueError(
                    "sync_every > 1 with grad_accum > 1 is not "
                    "supported: the local-SGD period already scans "
                    "sync_every microbatches per call")
        if probe_metrics and exchange.sync_every > 1:
            raise ValueError(
                "probe_metrics with sync_every > 1 is not supported: "
                "the local-SGD period has no single per-step global "
                "gradient to probe")
        if probe_metrics and device_data:
            raise ValueError(
                "probe_metrics does not compose with device_data=True "
                "(the staged-stream step has no probe output slot)")
        if zero:
            if fsdp:
                raise ValueError(
                    f"zero={zero} (chunk-major ZeRO) and fsdp=True "
                    "(the GSPMD dimension-sharded ZeRO-3 spelling) are "
                    "exclusive: they are alternative placements for "
                    "the same state")
            from distkeras_tpu.parallel.collectives import (
                DEFAULT_BUCKET_MB, zero1_enable, zero_validate)

            self._zero_bucket_mb = (DEFAULT_BUCKET_MB
                                    if zero_bucket_mb is None
                                    else zero_bucket_mb)
            # Satellite contract: the elementwise-compatibility check
            # runs at construction for EVERY stage — a known
            # non-elementwise transform (LARS/LAMB trust ratios) raises
            # naming itself instead of silently diverging inside the
            # scattered update.  Also rejects non-pure-DP meshes.
            # (Stage 1 runs it through zero1_enable, the shared
            # enablement path; stages 2/3 validate here and init over
            # views without a wrapper.)
            if zero != 1:
                zero_validate(self.mesh, optimizer, stage=zero)
            if zero == 1 and exchange.compress == "int8":
                from distkeras_tpu.parallel.exchange import (
                    exchange_optimizer)

                # zero1 x int8-EF: the exchange optimizer both shards
                # the update AND compresses the reduce-scatter leg.
                zero_validate(self.mesh, optimizer, stage=zero)
                self.optimizer = exchange_optimizer(
                    self.optimizer, self.mesh, exchange, zero1=True)
            elif zero == 1:
                # Wrap LAST, outside clip/EMA/weight-decay chains: the
                # whole chain then runs on shard views (the EMA shadow
                # and adam moments scatter too — the memory win covers
                # them all).
                self.optimizer = zero1_enable(
                    self.optimizer, self.mesh, spec=optimizer,
                    bucket_mb=self._zero_bucket_mb)
            else:
                # Stages 2/3 drive the raw chain on shard views from
                # inside the step (_make_zero_step); the trainer inits
                # its state over views directly, so no wrapper at all.
                self._zero_inner = self.optimizer
        elif exchange.needs_grad_exchange:
            from distkeras_tpu.parallel.exchange import exchange_optimizer

            self.optimizer = exchange_optimizer(
                self.optimizer, self.mesh, exchange)

        # segments (packed sequences) ride EVERY trunk: the default
        # flash attention, the ring (seq-axis) path — make_ring_attention
        # rotates the KV-side segment shard with its K/V — and the
        # pipelined trunk (per-microbatch segment slices ride the
        # pipeline as make_pipeline extras).
        if n_pipe > 1:
            # PP x SP: the pipeline shard_map goes manual over
            # {pipeline, seq} and runs the ring attention body per stage.
            # The head runs outside the pipeline, so with cfg.ce_chunks
            # the loss takes the trunk's hidden states (hidden_fn) and
            # chunks the vocab head exactly like the un-pipelined path.
            chunked = cfg.ce_chunks > 1
            def fwd(p, t, seg=None):
                return tfm.apply_pipelined(
                    p, t, cfg, self.mesh, microbatches=self.microbatches,
                    seq_axis="seq" if n_seq > 1 else None,
                    return_hidden=chunked, segment_ids=seg)
            # _forward_nll calls fwd(params, inputs, seg) so the trunk
            # masks attention, not just the loss.
            fwd.handles_segments = True
            self._fwd_kw = {"hidden_fn" if chunked else "apply_fn": fwd}
        elif n_seq > 1:
            ring = make_ring_attention(self.mesh, causal=True,
                                       window=cfg.attention_window)
            self._fwd_kw = {"attention_fn": ring}
        else:
            self._fwd_kw = {}
        # Replicated-DP (pure data mesh, replicated params): build the
        # gradient inside a shard_map so the tied embedding's two
        # cotangent contributions (lookup scatter + unembed dot) are
        # summed LOCALLY before one explicit per-leaf pmean — the
        # compiler-inserted exchange otherwise all-reduces them
        # separately (the graph lint's `comm-redundant-ar` finding:
        # 2x the embedding bytes on the wire every step).  Scoped to
        # exactly the configs where the exchange is the plain gradient
        # all-reduce: any sharded-param/sharded-update plan (fsdp,
        # zero1, TP/SP/PP axes) and MoE keep the compiler-inserted
        # collectives.
        dp_local_grads = (n_model == 1 and n_seq == 1 and n_pipe == 1
                          and int(self.mesh.shape["expert"]) == 1
                          and not fsdp and not zero
                          and not cfg.num_experts)
        if exchange.needs_grad_exchange:
            # Exchange configurations (adasum / EF codecs, zero1 x int8
            # included) feed the exchange optimizer STACKED per-replica
            # gradients instead of pmean'd ones.
            self._vag = self._stacked_local_value_and_grad()
        elif dp_local_grads:
            self._vag = self._dp_local_value_and_grad()
        else:
            self._vag = None
        # _fwd_kw captures the mesh-specific forward once; the step and
        # eval builders (and LoRATrainer's overrides) share it.
        if exchange.sync_every > 1:
            self._step_builder = lambda opt: _make_localsgd_step(
                cfg, opt, self.mesh, exchange)
        elif zero >= 2:
            self._step_builder = lambda opt: _make_zero_step(
                cfg, opt, self.mesh, self._layout(), stage=zero,
                grad_accum=grad_accum, probe=self.probe_metrics)
        else:
            self._step_builder = lambda opt: tfm.make_train_step(
                cfg, opt, grad_accum=grad_accum,
                value_and_grad=self._vag, probe=self.probe_metrics,
                **self._fwd_kw)
        self._nll_fn = lambda p, t, seg=None: tfm.lm_nll(
            p, t, cfg,
            segment_ids=seg,
            **self._fwd_kw)
        if zero >= 3:
            # Eval/serve read the params back out of the shard views:
            # gather per fusion bucket (jit-native all-gather), then
            # the unchanged nll — one gather per eval chunk, never per
            # train step.
            from distkeras_tpu.parallel.collectives import gather_bucket

            base_nll = self._nll_fn

            def nll_views(v, t, seg=None):
                layout = self._layout()
                full = layout.unpack(
                    [gather_bucket(b, self.mesh)
                     for b in layout.pack_views(v)])
                return base_nll(full, t, seg)

            self._nll_fn = nll_views

    @property
    def _feed_block(self) -> int:
        """Leading microbatch-block size of the fed token array: the
        grad-accum depth, or the local-SGD period (mutually exclusive
        by construction); 1 = a flat [B, S+1] batch."""
        return (self.grad_accum if self.grad_accum > 1
                else self.exchange.sync_every)

    def _layout(self):
        """The ZeRO fusion-bucket layout of this config's parameter
        tree (shapes only — eval_shape, nothing materializes); one
        geometry shared by the step builder, the view conversion, the
        eval gather and the sharding rules."""
        if self._zero_layout_cache is None:
            from distkeras_tpu.parallel.collectives import Zero1Layout

            shapes = jax.eval_shape(
                lambda: tfm.init_params(jax.random.key(self.seed),
                                        self.cfg))
            self._zero_layout_cache = Zero1Layout.for_tree(
                shapes, int(self.mesh.shape["data"]),
                self._zero_bucket_mb)
        return self._zero_layout_cache

    def _publish_tree(self, carry):
        """Live weight push: the carry is ``(params, opt_state)``;
        publish the params in parameter layout (one gather per bucket
        under stage 3, only on publish rounds)."""
        params, _ = carry
        if self.zero >= 3:
            params = self._layout().unview(params)
        return params

    def _dp_local_value_and_grad(self):
        """``jax.value_and_grad`` replacement for the replicated-DP
        configuration (see __init__): gradients are computed per
        replica inside a ``shard_map`` over the ``data`` axis — so
        autodiff's add of the tied embedding's two contributions is a
        LOCAL op — and exchanged with ONE explicit ``pmean`` per leaf.
        Identical math to the compiler-inserted all-reduce (the global
        batch mean's gradient is the mean of equal-sized shard
        gradients), at exactly parameter-bytes of all-reduce payload.

        Dropout and packed-segment runs fall back to the compiler-
        inserted exchange at trace time: the dropout mask stream and
        the valid-target count are *global-batch* quantities that a
        replica-local loss would compute differently.
        """
        mesh = self.mesh

        def value_and_grad(loss):
            vag = jax.value_and_grad(loss)

            def wrapped(params, tokens, cfg, attention_fn, apply_fn,
                        rng, hidden_fn, segment_ids=None):
                if rng is not None or segment_ids is not None:
                    return vag(params, tokens, cfg, attention_fn,
                               apply_fn, rng, hidden_fn, segment_ids)

                def local_grads(p, t):
                    l, g = vag(p, t, cfg, attention_fn, apply_fn,
                               None, hidden_fn, None)
                    def pm(x):
                        return jax.lax.pmean(x, "data")
                    return pm(l), jax.tree.map(pm, g)

                return shard_map(local_grads, mesh=mesh,
                                 in_specs=(P(), P("data", None)),
                                 out_specs=(P(), P()),
                                 check_vma=False)(params, tokens)

            return wrapped

        return value_and_grad

    def _stacked_local_value_and_grad(self):
        """``jax.value_and_grad`` replacement for the gradient-exchange
        configurations (parallel/exchange.py): per-replica gradients
        are computed inside a ``shard_map`` over ``data`` and returned
        STACKED — global ``[n, *leaf]`` sharded ``P("data")`` — for the
        exchange optimizer to merge (adasum / EF codecs; the
        compiler's pmean never runs).  The loss is pmean'd for
        reporting.  Dropout and packed segments are rejected at
        construction/train time, so the trace-time guard here is
        belt-and-braces."""
        mesh = self.mesh

        def value_and_grad(loss):
            vag = jax.value_and_grad(loss)

            def wrapped(params, tokens, cfg, attention_fn, apply_fn,
                        rng, hidden_fn, segment_ids=None):
                if rng is not None or segment_ids is not None:
                    raise ValueError(
                        "gradient-exchange configurations do not "
                        "support dropout or packed segments "
                        "(replica-local loss)")

                def local_grads(p, t):
                    l, g = vag(p, t, cfg, attention_fn, apply_fn,
                               None, hidden_fn, None)
                    g = jax.tree.map(lambda v: v[None], g)
                    return jax.lax.pmean(l, "data"), g

                return shard_map(local_grads, mesh=mesh,
                                 in_specs=(P(), P("data", None)),
                                 out_specs=(P(), P("data")),
                                 check_vma=False)(params, tokens)

            return wrapped

        return value_and_grad

    # ------------------------------------------------------------------

    @staticmethod
    def _put_global(tree, shardings):
        """Host pytree -> mesh-placed pytree, multi-process safe.

        Single process: plain ``device_put``.  Multi-process SPMD (the
        mesh spans hosts): every process holds the identical full host
        array (same-seeded parameter init), so each leaf is assembled
        per-shard via ``make_array_from_callback`` — ``device_put``
        cannot target non-addressable devices.  Per-host *data* (token
        batches, eval chunks) goes through :meth:`_global_batch`
        instead.
        """
        if jax.process_count() == 1:
            return jax.device_put(tree, shardings)

        def put(x, sh):
            x = np.asarray(x)
            return jax.make_array_from_callback(x.shape, sh,
                                                lambda idx: x[idx])

        return jax.tree.map(put, tree, shardings)

    # Per-step token blocks and eval chunks route through the shared
    # parallel.mesh.global_batch (one definition of the process-local
    # slab assembly for the whole trainer family).
    _global_batch = staticmethod(mesh_global_batch)

    def _guard_staged_bytes(self, n_rows: int, width: int,
                            with_segments: bool) -> None:
        """Fail fast when ``device_data=True`` would stage more HBM
        than the devices have, instead of surfacing as a raw XLA
        allocation error deep inside ``_global_batch`` (round-6 fix).

        The staged stream is int32 ``[rows, seq+1]`` sharded over the
        ``data`` axis (doubled when segments ride along), so each
        device persists ``rows * width * 4 / local_devices`` bytes for
        the whole run.  Backends that report a budget
        (``memory_stats``) get a hard error above
        ``_STAGING_FRACTION``; budget-less backends only warn past an
        absolute sanity bound.
        """
        n_local = int(self.mesh.shape["data"]) // jax.process_count()
        per_dev = (n_rows * width * 4 * (2 if with_segments else 1)
                   // max(n_local, 1))
        limit = _device_bytes_limit()
        msg = (f"device_data=True would stage "
               f"{per_dev / 2**20:.1f} MiB of token rows per device"
               + (" (segments included)" if with_segments else ""))
        if limit is not None and per_dev > _STAGING_FRACTION * limit:
            raise ValueError(
                f"{msg}, over {int(_STAGING_FRACTION * 100)}% of the "
                f"{limit / 2**20:.1f} MiB device budget — train with "
                "device_data=False (the streaming fallback), shard the "
                "corpus across more hosts, or trim the dataset")
        if limit is None and per_dev > _STAGING_SANITY_BYTES:
            import warnings

            warnings.warn(
                f"{msg}; this backend reports no memory budget, but "
                "that figure rarely fits — device_data=False streams "
                "from host instead", stacklevel=3)

    def _stage_stream(self, rows, steps):
        """Host token rows (consumption order) -> ONE device-resident
        int32 array sharded over the ``data`` axis, laid out so each
        device's shard is exactly its own consumption stream,
        contiguous — the LM form of ADAG._fit_device_data_multihost's
        stream layout.  Device ``(h, d)``'s stream position
        ``(step, accum, k)`` holds host h's row
        ``step*rows_per_step + accum*local_bs + d*sub + k`` — precisely
        the row the streaming path's ``_global_batch`` would place on
        that device — so an on-device ``take`` of a replicated index
        block reproduces streaming data order bit-for-bit.
        """
        n_proc = jax.process_count()
        n_data = int(self.mesh.shape["data"])
        n_local_dev = n_data // n_proc
        sub = self.batch_size // n_data
        a = np.asarray(rows, np.int32)
        a = a.reshape((steps, self.grad_accum, n_local_dev, sub)
                      + a.shape[1:])
        a = np.moveaxis(a, 2, 0)
        a = np.ascontiguousarray(a.reshape((len(rows),) + a.shape[4:]))
        return self._global_batch(a, NamedSharding(self.mesh,
                                                   P("data", None)))

    def _replicated(self, a):
        """Small replicated host array -> mesh.  NOT _global_batch:
        a replicated sharding must keep the local shape as the global
        shape (every host holds the identical copy), where the shared
        helper would concatenate hosts' rows."""
        return self._put_global(a, NamedSharding(self.mesh, P()))

    def init_params(self):
        params = tfm.init_params(jax.random.key(self.seed), self.cfg)
        return self._put_global(
            params, self.plan.tree_shardings(self.mesh, params))

    def _state_shardings(self, params, opt_state):
        """Sharding trees for (params, opt_state): subtrees of the
        optimizer state mirroring the params structure (adam mu/nu,
        momentum buffers) take the params' shardings; everything else
        (step counters) is replicated.

        Under the ZeRO stages the optimizer state instead holds
        ``[n, cols]`` shard views and takes the shared shard-view rule
        (``parallel/rules.py``); at stage 3 ``params`` is itself the
        view tree and scatters ``P("data", None)`` per leaf.
        """
        if self.zero >= 3:
            from distkeras_tpu.parallel.rules import (
                zero3_param_shardings)

            psh = zero3_param_shardings(params, self.mesh)
        else:
            psh = self.plan.tree_shardings(self.mesh, params)
        rep = NamedSharding(self.mesh, P())
        if self.exchange.needs_grad_exchange:
            # Exchange state: error-feedback residuals shard over
            # their replica axis (and shard views under zero1 x int8);
            # inner moments replicate like the (pure-DP) params.
            from distkeras_tpu.parallel.exchange import (
                exchange_state_shardings)

            return psh, exchange_state_shardings(
                params, opt_state, self.mesh, zero1=self.zero1)
        if self.zero:
            from distkeras_tpu.parallel.collectives import (
                zero1_state_shardings)

            return psh, zero1_state_shardings(params, opt_state,
                                              self.mesh)
        p_def = jax.tree.structure(params)

        def params_like(x):
            return jax.tree.structure(x) == p_def

        osh = jax.tree.map(lambda x: psh if params_like(x) else rep,
                           opt_state, is_leaf=params_like)
        return psh, osh

    def _build_carry_and_step(self, params):
        """Committed carry + THE jitted step for this configuration:
        ``(params, opt_state, psh, osh, step, step_sh, tok_sh)`` —
        ``train()``'s construction, also reached by ``bench_suite.py
        zero_stages`` so the bench times the exact program users train.

        Optimizer state must be *committed* to the mesh: fresh eager
        arrays are uncommitted (jit may reshard them freely) but the
        checkpoint-restore template takes each leaf's sharding
        literally, so adam's scalar count would come back pinned to
        one device while params span the mesh — an invalid mix.  Built
        under jit with explicit out_shardings (structure from
        eval_shape): eager optax init on params spanning
        non-addressable devices would fail multi-process.
        """
        if self.zero >= 2:
            # Stages 2/3 run the raw chain on shard views: the state
            # inits over the view tree (scattered moments), and at
            # stage 3 the persistent params themselves convert to the
            # ``[n, cols]`` view layout here — the carry trains as
            # views end to end.
            layout = self._layout()

            def init_views(p):
                return self.optimizer.init(layout.shard_views(p))

            opt_shapes = jax.eval_shape(init_views, params)
            carry_struct = (jax.eval_shape(layout.shard_views, params)
                            if self.zero >= 3 else params)
            psh, osh = self._state_shardings(carry_struct, opt_shapes)
            opt_state = jax.jit(init_views, out_shardings=osh)(params)
            if self.zero >= 3:
                params = jax.jit(layout.shard_views,
                                 out_shardings=psh)(params)
        else:
            opt_shapes = jax.eval_shape(self.optimizer.init, params)
            psh, osh = self._state_shardings(params, opt_shapes)
            opt_state = jax.jit(self.optimizer.init,
                                out_shardings=osh)(params)
        step, step_sh, tok_sh = self._jit_train_step(psh, osh)
        return params, opt_state, psh, osh, step, step_sh, tok_sh

    def _jit_train_step(self, psh, osh):
        """Build THE jitted optimizer step for this configuration —
        ``train`` and :meth:`traced_for_analysis` share this one
        construction so the IR lint audits the program that trains,
        never a reimplementation.  Returns ``(step, step_sh, tok_sh)``
        (the fed block's and the flat token rows' shardings)."""
        tok_sh = NamedSharding(self.mesh, P("data", None))
        # With accumulation (or a local-SGD period) the fed block is
        # [accum|sync_every, B, S+1]: the microbatch axis leads, batch
        # still shards over data.
        step_sh = (tok_sh if self._feed_block == 1
                   else NamedSharding(self.mesh, P(None, "data", None)))
        rep = NamedSharding(self.mesh, P())
        jit_kw = {}
        if int(self.mesh.shape["pipeline"]) == 1:
            # Pin the carry layout so XLA keeps the plan's placement
            # (scattered params under FSDP, Megatron splits under TP)
            # across steps instead of resharding at its own whim.
            # The pipelined trunk is exempt: its manual shard_map
            # governs placement internally.  rng and segment slots
            # are always present positionally (None when unused —
            # an empty pytree binds no sharding).
            if self.device_data:
                # The staged stream shares the token sharding: both
                # are [rows, S+1] split over the data axis.
                in_sh = ((psh, osh), tok_sh, rep, rep, tok_sh)
            else:
                in_sh = ((psh, osh), step_sh, rep, step_sh)
            jit_kw = dict(in_shardings=in_sh,
                          out_shardings=((psh, osh), rep))
        if self.device_data:
            # HBM-resident data plane: the staged stream stays on
            # device; each step ships only a replicated [accum, sub]
            # index block and a shard_map gathers every device's
            # rows from its OWN shard (a plain take on the sharded
            # array would all-gather the dataset each step).  The
            # gather fuses into the same XLA program as the step.
            inner = self._step_builder(self.optimizer)
            accum = self.grad_accum

            def local_take(xb, idx):
                g = jnp.take(xb, idx.reshape(-1), axis=0)
                return g.reshape(idx.shape + xb.shape[1:])

            gather = shard_map(
                local_take, mesh=self.mesh,
                in_specs=(P("data", None), P()),
                out_specs=(P(None, "data", None) if accum > 1
                           else P("data", None)),
                check_vma=False)

            def dd_step(carry, X, idx, rng, Seg):
                tok = gather(X, idx)
                seg = None if Seg is None else gather(Seg, idx)
                return inner(carry, tok, rng, seg)

            step = jax.jit(dd_step, donate_argnums=0, **jit_kw)
        else:
            step = jax.jit(self._step_builder(self.optimizer),
                           donate_argnums=0, **jit_kw)
        return step, step_sh, tok_sh

    def traced_for_analysis(self, seq_len: int | None = None,
                            n_rows: int | None = None):
        """Trace targets for the IR lint (analysis/ir_lint.py): the
        jitted train step this configuration executes, with example
        argument shapes for one optimizer round (``seq_len`` defaults
        to ``cfg.max_len``).  Under ``device_data=True`` the staged
        stream's aval depends on the corpus size — pass
        ``n_rows=len(tokens)`` to trace the exact program a concrete
        ``train(tokens)`` call compiles (default: one step's rows).
        Nothing executes and nothing is materialized — state is shape
        structs (``jax.eval_shape``), so a production-size trainer can
        be linted without touching HBM; the lint only traces and
        lowers."""
        from distkeras_tpu.analysis.ir_lint import TraceSpec

        seq = self.cfg.max_len if seq_len is None else seq_len
        params = jax.eval_shape(
            lambda: tfm.init_params(jax.random.key(self.seed),
                                    self.cfg))
        pbytes = int(sum(np.prod(v.shape) * v.dtype.itemsize
                         for v in jax.tree.leaves(params)))
        if self.zero >= 2:
            layout = self._layout()
            opt_state = jax.eval_shape(
                lambda p: self.optimizer.init(layout.shard_views(p)),
                params)
            if self.zero >= 3:
                params = jax.eval_shape(layout.shard_views, params)
        else:
            opt_state = jax.eval_shape(self.optimizer.init, params)
        psh, osh = self._state_shardings(params, opt_state)
        step, _, _ = self._jit_train_step(psh, osh)
        rng = (jax.random.key(self.seed + 0x5eed)
               if self.cfg.dropout > 0 else None)
        name = type(self).__name__.lower()
        variant = (f"zero{self.zero}" if self.zero
                   else "fsdp" if self.fsdp else "dp")
        if not self.exchange.is_default:
            label = self.exchange.label()
            variant = f"zero1_{label}" if self.zero1 else label
        # Shapes are the GLOBAL avals the jitted step consumes — the
        # same for every process count (multi-process hosts each feed
        # a block that _global_batch assembles into these).
        if self.device_data:
            n_data = int(self.mesh.shape["data"])
            sub = self.batch_size // n_data
            rows_per_step = self.batch_size * self.grad_accum
            rows = (rows_per_step if n_rows is None
                    else n_rows - n_rows % rows_per_step)
            X = jax.ShapeDtypeStruct((rows, seq + 1), jnp.int32)
            idx = jax.ShapeDtypeStruct(
                (self.grad_accum, sub) if self.grad_accum > 1
                else (sub,), jnp.int32)
            args = ((params, opt_state), X, idx, rng, None)
        else:
            block = self._feed_block
            shape = ((block, self.batch_size, seq + 1) if block > 1
                     else (self.batch_size, seq + 1))
            args = ((params, opt_state),
                    jax.ShapeDtypeStruct(shape, jnp.int32), rng, None)
        return [TraceSpec(name=f"{name}_{variant}/train_step", fn=step,
                          args=args, donate_argnums=(0,),
                          params_bytes=pbytes)]

    def train(self, dataset: Dataset | np.ndarray, params=None,
              eval_tokens: np.ndarray | None = None,
              segments: np.ndarray | None = None,
              eval_segments: np.ndarray | None = None):
        """Train over the token rows; returns the trained params pytree.

        ``eval_tokens [M, seq+1]`` (with ``eval_every``) runs a held-out
        NLL/perplexity evaluation every ``eval_every`` optimizer steps
        and once at the end (round -1) into ``eval_history``; fed in
        ``batch_size`` chunks, dropping a remainder of up to
        ``batch_size - 1`` rows (static shapes, one compiled program).

        ``segments`` (with optional ``eval_segments``): packed-sequence
        segment ids aligned with the rows (data/packing.pack_documents)
        — attention stays within-document and the loss skips boundary/
        padding targets.  Works on every mesh: data/model/fsdp/expert,
        the ``seq`` (ring) axis, and pipeline meshes (per-microbatch
        segment slices ride the pipeline).

        Multi-process: BOTH ``dataset`` and ``eval_tokens`` are this
        host's shard (e.g. ``rows[process_index::process_count]``), and
        every host must pass the same row counts — each eval chunk is
        ``batch_size / process_count`` local rows assembled into one
        global batch, so feeding the full set on every host would
        evaluate each row ``process_count`` times.
        """
        tokens = (dataset if isinstance(dataset, np.ndarray)
                  else dataset[self.tokens_col])
        if tokens.ndim != 2:
            raise ValueError(f"tokens must be [N, seq+1], got {tokens.shape}")
        if segments is not None:
            if segments.shape != tokens.shape:
                raise ValueError(
                    f"segments must align with the token rows "
                    f"{tokens.shape}, got {segments.shape}")
        if eval_segments is not None and segments is None:
            raise ValueError("eval_segments without segments — pack "
                             "train and eval the same way")
        if segments is not None and not self.exchange.is_default:
            raise ValueError(
                "packed segments do not compose with merge_rule/"
                "sync_every/compress: the valid-target count is a "
                "global-batch quantity a replica-local loss would "
                "compute differently")
        # Multi-process SPMD: every process runs this same loop over its
        # OWN rows (feed tokens[process_index::process_count] or
        # Dataset.shard) — all hosts must pass the same row count or
        # their step counts diverge and the collectives deadlock.
        n_proc = jax.process_count()
        n_data = int(self.mesh.shape["data"])
        n_seq = int(self.mesh.shape["seq"])
        seq_len = tokens.shape[1] - 1
        if n_seq > 1 and seq_len % n_seq:
            raise ValueError(
                f"sequence length {seq_len} (token rows carry seq+1 = "
                f"{tokens.shape[1]} positions) must divide by the mesh seq "
                f"axis ({n_seq}) for ring attention to shard it")
        global_bs = self.batch_size
        # The pipelined path splits each per-data-shard batch into
        # microbatches; without a pipeline axis only data divides it.
        divisor = n_data * (self.microbatches
                            if int(self.mesh.shape["pipeline"]) > 1 else 1)
        if global_bs % divisor:
            raise ValueError(
                f"batch_size={global_bs} must divide by data axis ({n_data})"
                + (f" x microbatches ({self.microbatches})"
                   if divisor != n_data else ""))
        if n_proc > 1 and n_data % n_proc:
            raise ValueError(
                f"multi-process training needs the data axis ({n_data}) to "
                f"divide by the process count ({n_proc}) so every host "
                "feeds its own devices' shards")
        if self.shuffle:
            # Same permutation contract as Dataset.shuffle; the row
            # gather runs through the native threaded loader when built.
            from distkeras_tpu.native import gather_rows

            perm = np.random.default_rng(self.seed).permutation(len(tokens))
            tokens = gather_rows(tokens, perm)  # gather_rows coerces to C-order
            if segments is not None:
                segments = gather_rows(segments, perm)

        self.eval_history = []
        if self.eval_every and eval_tokens is None:
            raise ValueError("eval_every is set but train() got no "
                             "eval_tokens")
        if eval_tokens is not None:
            if (eval_tokens.ndim != 2
                    or eval_tokens.shape[1] != tokens.shape[1]):
                raise ValueError(
                    f"eval_tokens must be [M, {tokens.shape[1]}] like the "
                    f"training rows, got {eval_tokens.shape}")
            if (eval_segments is not None
                    and eval_segments.shape != eval_tokens.shape):
                raise ValueError(
                    f"eval_segments must align with eval_tokens "
                    f"{eval_tokens.shape}, got {eval_segments.shape}")
            if len(eval_tokens) < global_bs // n_proc:
                raise ValueError(
                    f"eval_tokens has {len(eval_tokens)} rows; one eval "
                    f"batch needs {global_bs // n_proc} per process")

        # Per-run phase stats (and obs spans) describe THIS run only.
        self.step_timer.reset()
        t0 = time.perf_counter()
        # Fail fast on a bad checkpoint_dir before paying parameter
        # init and mesh placement.
        self._open_checkpoints()
        profiling = False
        try:
            if params is None:
                params = self.init_params()
            (params, opt_state, psh, osh, step, step_sh,
             tok_sh) = self._build_carry_and_step(params)
            dropping = self.cfg.dropout > 0
            # Dropout stream keyed on the optimizer round: resume from a
            # checkpoint replays the identical mask sequence.
            drop_base = (jax.random.key(self.seed + 0x5eed)
                         if dropping else None)

            eval_fn = None
            if eval_tokens is not None:
                from distkeras_tpu.utils.misc import nll_to_perplexity

                nll = jax.jit(self._nll_fn)
                eval_bs = global_bs // n_proc  # rows per process
                n_eval = len(eval_tokens) - (len(eval_tokens) % eval_bs)
                # Stage the eval chunks once; every eval round reuses
                # the device arrays instead of re-paying the transfer.
                eval_chunks = [
                    self._global_batch(
                        np.asarray(eval_tokens[j:j + eval_bs], np.int32),
                        tok_sh)
                    for j in range(0, n_eval, eval_bs)]
                eval_seg_chunks = eval_weights = None
                if eval_segments is not None:
                    eval_seg_chunks, eval_weights = [], []
                    for j in range(0, n_eval, eval_bs):
                        seg = np.asarray(eval_segments[j:j + eval_bs],
                                         np.int32)
                        gseg = self._global_batch(seg, tok_sh)
                        eval_seg_chunks.append(gseg)
                        # Packed chunks carry different VALID-target
                        # counts; each chunk's mean NLL must be
                        # weighted by its count or the corpus mean is
                        # biased toward padding-heavy tail chunks.
                        # Counted on the assembled GLOBAL chunk (not
                        # the host-local shard): nll() returns the
                        # global mean, and every process must weight
                        # it identically or multi-host eval_history
                        # desynchronizes.
                        eval_weights.append(int(jnp.sum(
                            (gseg[:, 1:] == gseg[:, :-1])
                            & (gseg[:, :-1] != 0))))

                def eval_fn(carry, rnd):
                    ps = carry[0]
                    if eval_seg_chunks is None:
                        mean = sum(float(nll(ps, c))
                                   for c in eval_chunks) / len(eval_chunks)
                    else:
                        tot = sum(w * float(nll(ps, c, sc))
                                  for c, sc, w in zip(
                                      eval_chunks, eval_seg_chunks,
                                      eval_weights))
                        mean = tot / max(sum(eval_weights), 1)
                    self.eval_history.append(
                        (rnd, {"loss": mean,
                               "perplexity": nll_to_perplexity(mean)}))

                if self.profile_dir and self.eval_every:
                    # Pre-compile the eval nll so an eval round landing
                    # inside the profiler capture window records eval
                    # *execution*, not its first-call XLA compile (the
                    # trace contract is steady-state work only).  With
                    # eval_every=0 no eval can land in the window.
                    jax.block_until_ready(
                        nll(params, eval_chunks[0]))

            carry, losses, probes = (params, opt_state), [], []
            # Multi-process: ``tokens`` holds only this host's rows, so
            # each step consumes 1/n_proc of the global row count and
            # the global batch is assembled shard-wise (_global_batch).
            # A local-SGD period (sync_every) consumes a block exactly
            # like grad_accum does — one leading microbatch axis.
            blk = self._feed_block
            rows_per_step = global_bs * blk // n_proc
            n_rows = len(tokens) - (len(tokens) % rows_per_step)
            if not n_rows:
                raise ValueError(
                    f"dataset has {len(tokens)} rows; one step needs "
                    f"{rows_per_step} (batch_size x grad_accum"
                    + (f" / {n_proc} processes)" if n_proc > 1 else ")"))
            X_dev = seg_dev = None
            if self.device_data:
                steps_pe = n_rows // rows_per_step
                self._guard_staged_bytes(n_rows, tokens.shape[1],
                                         segments is not None)
                X_dev = self._stage_stream(tokens[:n_rows], steps_pe)
                if segments is not None:
                    seg_dev = self._stage_stream(segments[:n_rows],
                                                 steps_pe)
            carry, start = self._restore_or(carry)
            rnd = 0
            # Profile rounds relative to the first *executed* round
            # (resume skips rnd <= start): one warm round for compile,
            # then profile_steps captured rounds.
            prof_start = start + 2
            for _ in range(self.num_epoch):
                for i in range(0, n_rows, rows_per_step):
                    rnd += 1
                    if rnd <= start:
                        continue
                    if self.device_data:
                        sub = global_bs // n_data
                        s = i // rows_per_step
                        flat = np.arange(s * self.grad_accum * sub,
                                         (s + 1) * self.grad_accum * sub,
                                         dtype=np.int32)
                        idx = (flat.reshape(self.grad_accum, sub)
                               if self.grad_accum > 1 else flat)
                        with self.step_timer.phase("h2d"):
                            step_args = (X_dev, self._replicated(idx))
                    else:
                        block = np.asarray(tokens[i:i + rows_per_step],
                                           np.int32)
                        seg_batch = None
                        if segments is not None:
                            seg_block = np.asarray(
                                segments[i:i + rows_per_step], np.int32)
                            if self.grad_accum > 1:
                                seg_block = seg_block.reshape(
                                    self.grad_accum, global_bs // n_proc,
                                    seg_block.shape[1])
                            seg_batch = self._global_batch(seg_block,
                                                           step_sh)
                        if blk > 1:
                            block = block.reshape(blk,
                                                  global_bs // n_proc,
                                                  block.shape[1])
                        with self.step_timer.phase("h2d"):
                            step_args = (self._global_batch(block,
                                                            step_sh),)
                    if self.profile_dir and rnd == prof_start:
                        jax.profiler.start_trace(self.profile_dir)
                        profiling = True
                    rng = (jax.random.fold_in(drop_base, rnd)
                           if dropping else None)
                    with self.step_timer.phase("step"):
                        if self.device_data:
                            carry, out = step(carry, *step_args, rng,
                                              seg_dev)
                        else:
                            carry, out = step(carry, *step_args, rng,
                                              seg_batch)
                    if self.probe_metrics:
                        loss, probe_aux = out
                        probes.append(probe_aux)
                    else:
                        loss = out
                    if (profiling
                            and rnd >= prof_start - 1 + self.profile_steps):
                        # Flush async device work ONCE, when the profile
                        # window closes — not a per-iteration sync.
                        jax.block_until_ready(loss)  # dkt: ignore[hot-sync]
                        jax.profiler.stop_trace()
                        profiling = False
                    losses.append(loss)
                    self._checkpoint(carry, rnd)
                    if (eval_fn is not None and self.eval_every
                            and rnd % self.eval_every == 0):
                        eval_fn(carry, rnd)
            if profiling:  # run shorter than the requested capture
                jax.block_until_ready(losses[-1])
                jax.profiler.stop_trace()
                profiling = False
            elif self.profile_dir and rnd < prof_start:
                import warnings

                warnings.warn(
                    f"profile_dir is set but the run executed only "
                    f"{max(0, rnd - start)} round(s); the trace skips the "
                    f"compile round and starts at round {prof_start - start}"
                    " — no profile was written. Train on more data or more "
                    "epochs to capture one.", stacklevel=2)
            if losses:
                self._checkpoint(carry, rnd, final=True)
            if eval_fn is not None and not (
                    self.eval_history and self.eval_history[-1][0] == rnd):
                eval_fn(carry, -1)  # final state not already evaluated
        finally:
            if profiling:  # exception mid-capture: close the profiler
                try:
                    jax.profiler.stop_trace()
                except Exception:
                    pass
            self._close_checkpoints()
        params, opt_state = carry
        if self.zero >= 3:
            # The carry trained as shard views; hand the user back a
            # params-layout tree (one gather per bucket, end of run).
            params = self._layout().unview(params)
        if self._ema:
            # Under a grad-exchange wrapper the state nests one level
            # deeper: (ema_state, ExchangeState).
            ema_src = (opt_state[0] if self.exchange.needs_grad_exchange
                       else opt_state)
            self._ema_params = ema_src[1]
            if self.zero:
                # The shadow rode the optimizer state as scattered
                # shard views; hand the user back a params-layout tree.
                self._ema_params = self._layout().unview(
                    self._ema_params)
        jax.block_until_ready(jax.tree.leaves(params)[0])
        self.history = [float(l) for l in losses]
        # Probe scalars and the exchange residual diagnostic retire in
        # ONE device->host pass at end of run, never per step.
        if probes:
            self.probe_history = [
                {k: float(v) for k, v in p.items()} for p in probes]
            for k, v in self.probe_history[-1].items():
                obs.gauge(f"train.{k}", v, trainer=type(self).__name__)
        if self.exchange.compress is not None:
            from distkeras_tpu.parallel.exchange import residual_norm_of

            rn = residual_norm_of(opt_state)
            if rn is not None:
                obs.gauge("exchange.residual_norm", rn)
                self.residual_norm = rn
        self.training_time = time.perf_counter() - t0
        self._record_run_metrics()
        return params


class LoRATrainer(LMTrainer):
    """Fine-tune a FROZEN pretrained base with LoRA adapters, under the
    exact LMTrainer contract (history, eval, shuffle, checkpoints,
    meshes, packing).

    ``base_params``: the pretrained tree (tfm.init_params layout, e.g.
    from ``dk.load_lm``).  The trained state is the packed
    ``(adapters, base)`` pair: the optimizer is wrapped in
    ``optax.masked`` so moments exist for the adapter leaves ONLY, the
    loss stop-gradients the base, and the step's base output aliases
    its input (donation keeps it in place).  ``train`` returns the
    MERGED servable params (``self.adapters`` keeps the raw delta —
    ship it with ``lora_merge`` for instant A/B of adapter versions).

    The merge runs inside the jitted step, so every LMTrainer mesh
    (TP, FSDP, ring, pipeline) and feature (grad_accum, segments,
    chunked CE) composes unchanged.  Checkpoints store the packed pair
    (base included — simple and correct; at LoRA scale the adapter
    delta is the only part that changes between steps).
    """

    def __init__(self, cfg: tfm.TransformerConfig, base_params,
                 lora_rank: int = 8, lora_alpha: float = 16.0,
                 lora_targets=("wq", "wv"), **kw):
        from distkeras_tpu.models.lora import (LoRAConfig, _validate,
                                               lora_mask, make_lora_loss)

        if base_params is None:
            raise ValueError(
                "LoRATrainer needs the pretrained base_params (load_lm "
                "or a trained LMTrainer tree) — LoRA over a random base "
                "is a sign the wrong trainer was picked")
        self.lora = LoRAConfig(rank=lora_rank, alpha=lora_alpha,
                               targets=tuple(lora_targets))
        _validate(cfg, self.lora)
        if kw.get("ema_decay") is not None:
            raise ValueError(
                "ema_decay is not supported on LoRATrainer: the "
                "adapter-masked optimizer state cannot shadow the "
                "frozen base; serve the merged tree train() returns "
                "(or EMA-average adapters outside the trainer)")
        if kw.get("zero1") or kw.get("zero"):
            raise ValueError(
                "zero1/zero= is not supported on LoRATrainer: the "
                "masked packed (adapters, base) state keeps moments "
                "only for the ~1000x-smaller adapter leaves, so there "
                "is nothing worth sharding — and the frozen base must "
                "stay whole for the in-step merge")
        if (kw.get("merge_rule", "mean") != "mean"
                or kw.get("sync_every", 1) != 1
                or kw.get("compress") is not None
                or kw.get("probe_metrics")):
            raise ValueError(
                "merge_rule/sync_every/compress/probe_metrics are not "
                "supported on LoRATrainer: the packed (adapters, base) "
                "gradient is ~1000x smaller than the base, so the "
                "exchange is never the bottleneck — and the builders "
                "here bypass the exchange-aware step construction")
        super().__init__(cfg, **kw)
        self.optimizer = optax.masked(self.optimizer, lora_mask)
        self._base_host = base_params
        self.adapters = None
        loss_fn = make_lora_loss(cfg, self.lora)
        fwd_kw = self._fwd_kw
        # Deliberately WITHOUT the parent's value_and_grad hook
        # (_dp_local_value_and_grad): the tied-embedding redundancy it
        # fixes cannot occur here — the base (embedding included) is
        # stop-gradiented, so its cotangent is a symbolic zero with no
        # all-reduce at all — while the shard_map path's per-leaf
        # pmean would ADD explicit collectives over the base-sized
        # zero gradient leaves the compiler currently elides.
        self._step_builder = lambda opt: tfm.make_train_step(
            cfg, opt, grad_accum=self.grad_accum, loss_fn=loss_fn,
            **fwd_kw)

        def nll(packed, t, seg=None):
            from distkeras_tpu.models.lora import lora_merge

            adapters, base = packed
            merged = lora_merge(base, adapters, cfg, self.lora)
            return tfm.lm_nll(
                merged, t, cfg,
                segment_ids=seg,
                **fwd_kw)

        self._nll_fn = nll

    def init_params(self):
        from distkeras_tpu.models.lora import lora_init

        adapters = lora_init(jax.random.key(self.seed + 1), self.cfg,
                             self.lora)
        # COPY the base into the packed state: the train loop donates
        # its carry (the base aliases through the step, which is the
        # point), so without a copy the first step would consume the
        # caller's buffers and a second train()/serve on the same base
        # would hit "Array has been deleted".
        base = jax.tree.map(lambda x: jnp.array(x, copy=True),
                            self._base_host)
        packed = (adapters, base)
        return self._put_global(
            packed, self.plan.tree_shardings(self.mesh, packed))

    def train(self, dataset, params=None, **kw):
        from distkeras_tpu.models.lora import lora_merge

        if params is not None:
            raise ValueError(
                "LoRATrainer builds its own (adapters, base) state from "
                "the constructor's base_params; to resume, use "
                "checkpoint_dir/resume like any trainer")
        packed = super().train(dataset, **kw)
        self.adapters, base = packed
        return lora_merge(base, self.adapters, self.cfg, self.lora)
