"""Trainer base + SingleTrainer (reference parity: distkeras/trainers.py).

API contract kept from the reference: construct with a Keras model,
loss, optimizer and knobs; ``train(dataset) -> trained keras model``;
``training_time`` attribute records the wall clock of the run
(reference: Trainer.train records training_time; SURVEY.md §5 notes it
is the reference's only perf signal).  ``history`` additionally records
per-step losses — strictly more observability than the reference.
"""

from __future__ import annotations

import time

import jax
import numpy as np

from distkeras_tpu import obs
from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.models.adapter import ModelAdapter
from distkeras_tpu.resilience import chaos
from distkeras_tpu.resilience.chaos import Preempted
from distkeras_tpu.utils.profiling import StepTimer


def normalize_zero_args(zero, zero1: bool, zero_bucket_mb,
                        zero1_bucket_mb):
    """Reconcile the ``zero=`` stage knob with its deprecated PR-2
    aliases — ONE definition for both trainer families
    (``DistributedTrainer`` and ``LMTrainer``), so the alias semantics
    can never drift between them.  Returns
    ``(zero, zero1, zero_bucket_mb)`` with ``zero1 == (zero == 1)``.
    """
    if zero is None:
        zero = 1 if zero1 else 0
    elif zero1 and zero != 1:
        raise ValueError(
            f"zero1=True is the deprecated alias of zero=1 and "
            f"cannot combine with zero={zero}; pass zero= alone")
    if zero not in (0, 1, 2, 3):
        raise ValueError(
            f"zero must be 0 (off), 1, 2 or 3, got {zero!r}")
    if zero_bucket_mb is not None and zero1_bucket_mb is not None:
        raise ValueError(
            "pass only one of zero_bucket_mb / zero1_bucket_mb "
            "(the latter is the deprecated alias)")
    if zero_bucket_mb is None:
        zero_bucket_mb = zero1_bucket_mb
    return zero, zero == 1, zero_bucket_mb


class CheckpointingBase:
    """Checkpoint/resume plumbing shared across the whole trainer family.

    The Keras trainers (:class:`Trainer` subclasses) and the flagship
    :class:`~distkeras_tpu.trainers.lm.LMTrainer` persist training state
    through the same orbax-backed machinery so the user contract —
    ``checkpoint_dir`` / ``checkpoint_every`` / ``max_checkpoints`` /
    ``resume`` — is uniform, the way the reference keeps one contract
    across its trainer family (reference: distkeras/trainers.py base
    class).
    """

    def _setup_checkpointing(self, *, checkpoint_dir: str | None,
                             checkpoint_every: int, max_checkpoints: int,
                             resume: bool, shuffle: bool,
                             seed: int | None,
                             backend: str = "auto") -> None:
        self.checkpoint_every = checkpoint_every
        self.resume = resume
        self.checkpoint_dir = checkpoint_dir
        self.max_checkpoints = max_checkpoints
        self.checkpoint_backend = backend
        # Set by a resilience.Supervisor (or any orchestrator): when
        # this Event is set, the next round boundary forces a final
        # synchronous checkpoint and raises Preempted — the graceful
        # half of a preemption.
        self.preempt_event = None
        self._ckpt = None
        self._last_saved_round = 0
        if resume and shuffle and seed is None:
            raise ValueError(
                "resume=True with shuffle=True needs a fixed seed: resume "
                "skips the first N rounds of the stream, which only lands on "
                "the right data if the permutation is reproducible")
        if (resume or checkpoint_every) and not checkpoint_dir:
            raise ValueError(
                "resume/checkpoint_every need a checkpoint_dir — without one "
                "nothing is restored or written")

    def _open_checkpoints(self) -> None:
        """Open the per-run checkpoint manager (closed by _close_)."""
        self._last_saved_round = 0
        if not self.checkpoint_dir:
            return
        from distkeras_tpu.checkpoint import CheckpointManager

        # Opened per run and closed on exit so orbax's async machinery
        # doesn't outlive the training it serves.
        self._ckpt = CheckpointManager(
            self.checkpoint_dir, max_to_keep=self.max_checkpoints,
            backend=self.checkpoint_backend)
        if not self.resume and self._ckpt.latest_step() is not None:
            self._ckpt.close()
            self._ckpt = None
            raise ValueError(
                f"checkpoint_dir {self.checkpoint_dir!r} already holds "
                "checkpoints; pass resume=True to continue from them or "
                "point at a fresh directory (orbax refuses to overwrite "
                "an existing step)")

    def _close_checkpoints(self) -> None:
        if self._ckpt is not None:
            self._ckpt.close()
            self._ckpt = None

    def _restore_or(self, pytree):
        """Return (pytree, start_round): latest checkpoint if resuming.

        Resume semantics: deterministic data order; the first
        ``start_round`` rounds of the batch stream are skipped so the
        restored state continues exactly where the checkpoint left off.
        """
        if not (self._ckpt and self.resume):
            return pytree, 0
        step = self._ckpt.latest_step()
        if step is None:
            return pytree, 0
        valid = self._ckpt.latest_valid_step()
        if valid != step:
            # Torn latest (host died mid-save on a store without
            # atomic rename): resume from the newest step that passes
            # the integrity check instead of crashing inside restore —
            # the same selection rule the cluster-consistent restart
            # applies across hosts.
            import warnings

            from distkeras_tpu.resilience.cluster import (
                trim_to_consistent)

            warnings.warn(
                f"checkpoint step {step} under "
                f"{self.checkpoint_dir!r} is torn/partial; resuming "
                f"from the latest valid step {valid} instead",
                stacklevel=2)
            obs.event("checkpoint.torn", step=step, fallback=valid)
            # Drop the torn steps: the resumed run will pass their
            # rounds again, and both backends refuse to overwrite a
            # step directory that (half-)exists.  One trimming rule,
            # shared with the cluster driver's pre-epoch trim.
            trim_to_consistent([self._ckpt.directory])
            if valid is None:
                return pytree, 0
            step = valid
        with obs.span("checkpoint.restore", step=step):
            restored = self._ckpt.restore(pytree, step)
        return restored, step

    def attach_publisher(self, publisher, every: int = 1):
        """Wire a :class:`~distkeras_tpu.serving.publish.
        SnapshotPublisher` into the round loop: every ``every`` rounds
        (and on the final round) the trainer publishes its current
        weights as snapshot version ``round_idx`` — the trainer side
        of the live train→serve weight push (docs/serving_guide.md).

        Publishing is independent of checkpointing: a trainer with no
        ``checkpoint_dir`` still publishes.  The snapshot version IS
        the round index, so versions are monotone across a resumed
        run for free.  Returns ``self`` for chaining."""
        if every < 1:
            raise ValueError(f"every must be >= 1, got {every}")
        self._publisher = publisher
        self._publish_every = int(every)
        self._last_published = 0
        return self

    def _publish_tree(self, pytree):
        """The weights to publish, extracted from the round-loop state.
        Subclasses override to unwrap their carry (and un-view ZeRO-3
        shard views); the base publishes the state as-is."""
        return pytree

    def _maybe_publish(self, pytree, round_idx: int,
                       final: bool = False) -> None:
        pub = getattr(self, "_publisher", None)
        if pub is None or round_idx == self._last_published:
            return
        if final or round_idx % self._publish_every == 0:
            with obs.span("publish.snapshot", step=round_idx):
                pub.publish(self._publish_tree(pytree), round_idx)
            self._last_published = round_idx

    def _checkpoint(self, pytree, round_idx: int, final: bool = False) -> None:
        """Persist training state after round ``round_idx`` (1-based).

        Blocks until the save is durable: the round loop donates state
        buffers into the next step, so an in-flight async write must not
        alias them.  States at dist-keras scale write in milliseconds.
        """
        chaos.probe("train.round", step=round_idx)
        self._maybe_publish(pytree, round_idx, final)
        if self.preempt_event is not None and self.preempt_event.is_set():
            # Graceful preemption (SIGTERM via a Supervisor, or any
            # orchestrator flipping the event): persist THIS round's
            # state synchronously, then stop.  The resumed run replays
            # from here bit-for-bit — data order is round-indexed and
            # every RNG stream is keyed on the round counter.
            if self._ckpt is not None and round_idx != self._last_saved_round:
                with obs.span("checkpoint.save", step=round_idx,
                              preempt=True):
                    self._ckpt.save(pytree, round_idx, force=True)
                    self._ckpt.wait_until_finished()
                self._last_saved_round = round_idx
            obs.event("train.preempted", round=round_idx,
                      checkpointed=self._ckpt is not None)
            raise Preempted(
                f"preempted at round {round_idx}"
                + (" (state checkpointed)" if self._ckpt is not None
                   else " (no checkpoint_dir: round lost)"))
        if self._ckpt is None or round_idx == self._last_saved_round:
            return  # (final save right after a periodic one: already durable)
        periodic = self.checkpoint_every and round_idx % self.checkpoint_every == 0
        if final or periodic:
            with obs.span("checkpoint.save", step=round_idx):
                self._ckpt.save(pytree, round_idx, force=True)
                self._ckpt.wait_until_finished()
            self._last_saved_round = round_idx

    def _record_run_metrics(self) -> None:
        """End-of-run telemetry (obs, docs/observability.md): loss and
        timing gauges from state the run already computed host-side —
        never a per-step device sync, never an extra compiled program
        (the zero-overhead contract the obs smoke test pins)."""
        if obs.active() is None:
            return
        name = type(self).__name__
        obs.gauge("train.training_time_s", self.training_time,
                  trainer=name)
        hist = getattr(self, "history", None)
        if hist:
            obs.gauge("train.loss", hist[-1], trainer=name)
            obs.gauge("train.loss_mean", sum(hist) / len(hist),
                      trainer=name)
            obs.count("train.rounds", len(hist), trainer=name)
        for phase, st in self.step_timer.phase_stats().items():
            obs.gauge("train.phase_total_s", st["total_s"],
                      trainer=name, phase=phase)


class Trainer(CheckpointingBase):
    """Base trainer: owns the adapter and the train() bookkeeping."""

    def __init__(self, keras_model, loss="categorical_crossentropy",
                 worker_optimizer="sgd", learning_rate: float | None = None,
                 batch_size: int = 32, num_epoch: int = 1,
                 features_col: str = "features", label_col: str = "label",
                 shuffle: bool = False, seed: int | None = None,
                 checkpoint_dir: str | None = None, checkpoint_every: int = 0,
                 max_checkpoints: int = 3, resume: bool = False,
                 checkpoint_backend: str = "auto",
                 preprocess=None, metrics=(), eval_every: int = 0):
        self.adapter = ModelAdapter(
            keras_model, loss=loss, optimizer=worker_optimizer,
            learning_rate=learning_rate, preprocess=preprocess,
            metrics=metrics)
        # Mid-training evaluation: every ``eval_every`` rounds (and once
        # at the end) the trainer runs the adapter's eval fn over the
        # eval dataset passed to train(), appending
        # ``(round, {"loss": ..., metric...})`` to ``eval_history``.
        self.eval_every = eval_every
        self.eval_history: list[tuple[int, dict]] = []
        self._eval_batch = None
        self._eval_chunks = None   # multi-process: pre-staged global chunks
        self._eval_fn = None
        self.batch_size = batch_size
        self.num_epoch = num_epoch
        self.features_col = features_col
        self.label_col = label_col
        self.shuffle = shuffle
        self.seed = seed
        self.training_time: float = 0.0
        self.history: list[float] = []
        # Per-run phase observability (utils/profiling.StepTimer): the
        # distributed trainers populate "h2d" (host staging + transfer
        # dispatch) and "step" (jitted dispatch) so an input-bound run
        # reads differently from a compute-bound one without a profile.
        self.step_timer = StepTimer()
        # Checkpoint/resume (SURVEY.md §5: the reference has none; here
        # any trainer can persist its full training state via orbax).
        self._setup_checkpointing(
            checkpoint_dir=checkpoint_dir, checkpoint_every=checkpoint_every,
            max_checkpoints=max_checkpoints, resume=resume, shuffle=shuffle,
            seed=seed, backend=checkpoint_backend)

    # -- subclass hook -----------------------------------------------------
    def _fit(self, dataset: Dataset):  # pragma: no cover
        raise NotImplementedError

    def train(self, dataset: Dataset, features_col: str | None = None,
              label_col: str | None = None,
              eval_dataset: Dataset | None = None):
        """Train and return a fresh Keras model with the learned weights.

        (EnsembleTrainer returns a list of models via its ``_export``.)
        ``eval_dataset`` feeds the ``eval_every`` hook (see __init__);
        passing one without ``eval_every`` evaluates once, at the end.
        """
        if features_col:
            self.features_col = features_col
        if label_col:
            self.label_col = label_col
        if self.shuffle:
            dataset = dataset.shuffle(self.seed)
        self.eval_history = []
        self._eval_batch = None
        self._eval_chunks = None
        if eval_dataset is not None:
            if jax.process_count() > 1:
                self._stage_eval_chunks(eval_dataset)
            elif len(eval_dataset) == 0:
                raise ValueError("eval_dataset is empty")
            else:
                self._eval_batch = (eval_dataset[self.features_col],
                                    eval_dataset[self.label_col])
            self._eval_fn = jax.jit(self.adapter.make_eval_fn())
        elif self.eval_every:
            raise ValueError(
                "eval_every is set but train() got no eval_dataset")
        # Per-run observability: phase stats describe THIS run only
        # (explicit reset — reuse across train() calls must not blend
        # runs), and the whole run is one obs span.
        self.step_timer.reset()
        t0 = time.perf_counter()
        self._open_checkpoints()
        try:
            with obs.span("train.run", trainer=type(self).__name__):
                state = self._fit(dataset)
                self._eval_hook(state, rnd=None, final=True)
                jax.block_until_ready(state.tv)
        finally:
            self._close_checkpoints()
        self.training_time = time.perf_counter() - t0
        self._record_run_metrics()
        return self._export(state)

    # -- evaluation hook ---------------------------------------------------
    def _stage_eval_chunks(self, eval_dataset: Dataset) -> None:
        """Multi-process eval: pre-stage the (host-local) eval shard as
        globally-sharded chunks of exactly the training microbatch
        geometry, mirroring LMTrainer's eval-chunk plumbing.

        Each host contributes ``global_bs / process_count`` rows per
        chunk (``_global_batch`` assembles the global array from the
        process-local slabs); the jitted eval fn then computes the
        global mean with compiler-inserted collectives and returns it
        replicated, so every host records identical eval_history.  The
        collective cadence requires every host to pass an eval shard
        with the SAME row count (checked up front); the tail remainder
        that doesn't fill a chunk is dropped, as in training.

        Only the host-side slabs are kept here; each global chunk is
        assembled on device when an eval round actually fires
        (_eval_hook) — pinning the whole eval set in HBM for the run
        would cut into training memory, the thing the single-process
        path's mini-batching exists to protect.
        """
        from distkeras_tpu.parallel.mesh import (equal_across_hosts,
                                                  per_host_rows)

        mesh = getattr(self, "mesh", None)
        if mesh is None:
            raise ValueError(
                "eval_dataset in the multi-process runtime needs a mesh "
                "trainer (the distributed/elastic family or LMTrainer); "
                "SingleTrainer has no cross-host eval plane")
        pcount = jax.process_count()
        feed = per_host_rows(self.batch_size * self.num_workers,
                             what="eval-chunk global batch")
        equal_across_hosts(len(eval_dataset), "eval shard sizes")
        usable = len(eval_dataset) - len(eval_dataset) % feed
        if usable == 0:
            raise ValueError(
                f"eval_dataset holds {len(eval_dataset)} rows per host "
                f"but one eval chunk needs {feed} "
                "(batch_size x num_workers / process_count)")
        if usable < len(eval_dataset):
            import warnings

            # The single-process path mini-batches ALL rows, so a
            # ragged shard silently diverges from that run's metrics
            # unless the caller is told (advisor round-4).
            warnings.warn(
                f"multi-process eval uses {usable} of "
                f"{len(eval_dataset)} eval rows per host (chunks of "
                f"{feed}); the {len(eval_dataset) - usable}-row tail is "
                "excluded from eval metrics on every host — pad or trim "
                "the shard to a multiple of the chunk size for "
                "single-process-identical numbers", stacklevel=3)
        x = np.asarray(eval_dataset[self.features_col])
        y = np.asarray(eval_dataset[self.label_col])
        sh = self._batch_sharding(leading_window=False)
        self._eval_chunks = (
            [(x[j:j + feed], y[j:j + feed], feed * pcount)
             for j in range(0, usable, feed)], sh)

    def _eval_state_view(self, pytree):
        """(tv, ntv) of the evaluable model inside a fit-loop pytree."""
        return pytree.tv, pytree.ntv

    def _eval_hook(self, pytree, rnd, final: bool = False) -> None:
        """Record eval metrics at round ``rnd``; the end-of-training
        call records round -1 (always runs when an eval set exists)."""
        if self._eval_batch is None and self._eval_chunks is None:
            return
        if not final and not (self.eval_every and rnd % self.eval_every == 0):
            return
        tv, ntv = self._eval_state_view(pytree)
        sums, n = {}, 0
        if self._eval_chunks is not None:
            # Multi-process: host slabs are assembled into globally-
            # sharded chunks only when an eval round fires; the eval
            # outputs are replicated scalars (global means via the
            # compiled collectives), identical on every host.
            slabs, sh = self._eval_chunks
            for xb, yb, rows in slabs:
                part = self._eval_fn(tv, ntv,
                                     self._global_batch(xb, sh),
                                     self._global_batch(yb, sh))
                for k, v in part.items():
                    sums[k] = sums.get(k, 0.0) + float(v) * rows
                n += rows
        else:
            x, y = self._eval_batch
            # Mini-batch the eval set (at the training batch size) so a
            # large eval split never materializes all activations at
            # once; at most two compiled shapes (full + one remainder).
            bs = min(self.batch_size, len(x))
            for i in range(0, len(x), bs):
                xb, yb = x[i:i + bs], y[i:i + bs]
                part = self._eval_fn(tv, ntv, xb, yb)
                for k, v in part.items():
                    sums[k] = sums.get(k, 0.0) + float(v) * len(xb)
                n += len(xb)
        out = {k: v / n for k, v in sums.items()}
        self.eval_history.append((-1 if final else rnd, out))

    def _export(self, state):
        return self.adapter.export_model(state)

    # -- helpers -----------------------------------------------------------
    def _epoch_stream(self, dataset: Dataset, window: int | None = None):
        """Yield (x, y) batches across all epochs."""
        for _ in range(self.num_epoch):
            ds = dataset
            yield from ds.batches(
                self.batch_size, features_col=self.features_col,
                label_col=self.label_col, drop_remainder=True, window=window)

    def _record(self, losses) -> None:
        self.history.extend(float(l) for l in losses)

    def _require_steps(self, losses, rows_needed: int, n_rows: int) -> None:
        """Refuse to silently return an untrained model.

        Every trainer needs at least ``rows_needed`` rows to form one
        step; with fewer, the batch stream is empty and training would
        be a no-op the user can't distinguish from success.
        """
        if not losses:
            raise ValueError(
                f"dataset has {n_rows} rows but one training step needs "
                f"{rows_needed} (batch_size x num_workers x window); "
                "reduce batch_size/communication_window/num_workers or "
                "provide more data")


class SingleTrainer(Trainer):
    """Single-device training: one jitted step, a Python loop over batches.

    Reference parity: distkeras/trainers.py::SingleTrainer +
    distkeras/workers.py::SingleTrainerWorker (one partition, sequential
    ``train_on_batch`` loop — SURVEY.md §3.1).  Here the step is one XLA
    program; the loop merely feeds batches and retires device losses
    without forcing a sync every step.

    ``steps_per_call`` > 1 scans that many optimizer updates inside one
    XLA call (adapter.make_multi_train_step), amortizing host dispatch —
    the dominant cost for small models.  Checkpoint granularity becomes
    ``steps_per_call`` steps; a round = one call; like the windowed
    distributed trainers, each epoch drops its tail remainder of up to
    ``steps_per_call * batch_size - 1`` rows (shapes must stay static).

    ``device_data=True`` stages the dataset columns in device memory
    once and feeds each round an int32 index block instead of batch
    payloads (adapter.make_indexed_train_step): after the one-time
    staging transfer, only ~4 bytes/sample/epoch cross the
    host->device link.  The right mode whenever the dataset fits in
    HBM (CIFAR-scale and far beyond) — the host link is the input
    pipeline's narrow point, especially on remote-attached devices.
    Identical math and data order to the streaming path.
    """

    def __init__(self, keras_model, loss="categorical_crossentropy", *,
                 steps_per_call: int = 1, device_data: bool = False, **kw):
        # steps_per_call is keyword-only so the parent's positional
        # contract (keras_model, loss, ...) is preserved.
        super().__init__(keras_model, loss=loss, **kw)
        if steps_per_call < 1:
            raise ValueError(f"steps_per_call must be >= 1, got {steps_per_call}")
        self.steps_per_call = steps_per_call
        self.device_data = device_data

    def _fit(self, dataset: Dataset):
        spc = self.steps_per_call
        state = self.adapter.init_state()
        state, start = self._restore_or(state)
        if start and int(state.step) != start * spc:
            raise ValueError(
                f"checkpoint at round {start} holds optimizer step "
                f"{int(state.step)}, but steps_per_call={spc} implies "
                f"{start * spc}: the checkpoint was written under a "
                "different steps_per_call — resume with the original "
                "value (data skipping is counted in rounds)")
        if self.device_data:
            step = jax.jit(self.adapter.make_indexed_train_step(spc),
                           donate_argnums=0)
            X = jax.device_put(dataset[self.features_col])
            Y = jax.device_put(dataset[self.label_col])
            n = len(dataset)
            rows = self.batch_size * spc

            def stream():
                for _ in range(self.num_epoch):
                    for i in range(0, n - (n % rows), rows):
                        yield (X, Y,
                               np.arange(i, i + rows, dtype=np.int32)
                               .reshape(spc, self.batch_size))
            stream = stream()
        elif spc == 1:
            step = jax.jit(self.adapter.make_train_step(), donate_argnums=0)
            stream = self._epoch_stream(dataset)
        else:
            step = jax.jit(self.adapter.make_multi_train_step(spc),
                           donate_argnums=0)
            stream = self._epoch_stream(dataset, window=spc)
        losses, rnd = [], start
        for rnd, args in enumerate(stream, 1):
            if rnd <= start:
                continue
            state, loss = step(state, *args)
            # Device array (scalar, or [spc] when scanning); no sync here.
            losses.append(loss)
            self._checkpoint(state, rnd)
            self._eval_hook(state, rnd)
        if start and not losses:  # resumed past the end: nothing left to do
            return state
        self._require_steps(losses, self.batch_size * spc, len(dataset))
        self._record(np.concatenate([np.atleast_1d(l) for l in losses]))
        self._checkpoint(state, rnd, final=True)
        return state
