"""Trainer base + SingleTrainer (reference parity: distkeras/trainers.py).

API contract kept from the reference: construct with a Keras model,
loss, optimizer and knobs; ``train(dataset) -> trained keras model``;
``training_time`` attribute records the wall clock of the run
(reference: Trainer.train records training_time; SURVEY.md §5 notes it
is the reference's only perf signal).  ``history`` additionally records
per-step losses — strictly more observability than the reference.
"""

from __future__ import annotations

import time

import jax
import numpy as np

from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.models.adapter import ModelAdapter


class Trainer:
    """Base trainer: owns the adapter and the train() bookkeeping."""

    def __init__(self, keras_model, loss="categorical_crossentropy",
                 worker_optimizer="sgd", learning_rate: float | None = None,
                 batch_size: int = 32, num_epoch: int = 1,
                 features_col: str = "features", label_col: str = "label",
                 shuffle: bool = False, seed: int | None = None):
        self.adapter = ModelAdapter(
            keras_model, loss=loss, optimizer=worker_optimizer,
            learning_rate=learning_rate)
        self.batch_size = batch_size
        self.num_epoch = num_epoch
        self.features_col = features_col
        self.label_col = label_col
        self.shuffle = shuffle
        self.seed = seed
        self.training_time: float = 0.0
        self.history: list[float] = []

    # -- subclass hook -----------------------------------------------------
    def _fit(self, dataset: Dataset):  # pragma: no cover
        raise NotImplementedError

    def train(self, dataset: Dataset, features_col: str | None = None,
              label_col: str | None = None):
        """Train and return a fresh Keras model with the learned weights.

        (EnsembleTrainer returns a list of models via its ``_export``.)
        """
        if features_col:
            self.features_col = features_col
        if label_col:
            self.label_col = label_col
        if self.shuffle:
            dataset = dataset.shuffle(self.seed)
        t0 = time.perf_counter()
        state = self._fit(dataset)
        jax.block_until_ready(state.tv)
        self.training_time = time.perf_counter() - t0
        return self._export(state)

    def _export(self, state):
        return self.adapter.export_model(state)

    # -- helpers -----------------------------------------------------------
    def _epoch_stream(self, dataset: Dataset, window: int | None = None):
        """Yield (x, y) batches across all epochs."""
        for _ in range(self.num_epoch):
            ds = dataset
            yield from ds.batches(
                self.batch_size, features_col=self.features_col,
                label_col=self.label_col, drop_remainder=True, window=window)

    def _record(self, losses) -> None:
        self.history.extend(float(l) for l in losses)

    def _require_steps(self, losses, rows_needed: int, n_rows: int) -> None:
        """Refuse to silently return an untrained model.

        Every trainer needs at least ``rows_needed`` rows to form one
        step; with fewer, the batch stream is empty and training would
        be a no-op the user can't distinguish from success.
        """
        if not losses:
            raise ValueError(
                f"dataset has {n_rows} rows but one training step needs "
                f"{rows_needed} (batch_size x num_workers x window); "
                "reduce batch_size/communication_window/num_workers or "
                "provide more data")


class SingleTrainer(Trainer):
    """Single-device training: one jitted step, a Python loop over batches.

    Reference parity: distkeras/trainers.py::SingleTrainer +
    distkeras/workers.py::SingleTrainerWorker (one partition, sequential
    ``train_on_batch`` loop — SURVEY.md §3.1).  Here the step is one XLA
    program; the loop merely feeds batches and retires device losses
    without forcing a sync every step.
    """

    def _fit(self, dataset: Dataset):
        state = self.adapter.init_state()
        step = jax.jit(self.adapter.make_train_step(), donate_argnums=0)
        losses = []
        for x, y in self._epoch_stream(dataset):
            state, loss = step(state, x, y)
            losses.append(loss)  # device array; no sync here
        self._require_steps(losses, self.batch_size, len(dataset))
        self._record(losses)
        return state
