from distkeras_tpu.trainers.base import Trainer, SingleTrainer
from distkeras_tpu.trainers.distributed import (
    DistributedTrainer,
    ADAG,
    DynSGD,
)
from distkeras_tpu.trainers.async_dp import AsyncDP
from distkeras_tpu.trainers.lm import LMTrainer, LoRATrainer
from distkeras_tpu.trainers.elastic import (
    AEASGD,
    EAMSGD,
    DOWNPOUR,
    AveragingTrainer,
    EnsembleTrainer,
)

__all__ = [
    "Trainer",
    "SingleTrainer",
    "DistributedTrainer",
    "ADAG",
    "AsyncDP",
    "DynSGD",
    "AEASGD",
    "EAMSGD",
    "DOWNPOUR",
    "AveragingTrainer",
    "EnsembleTrainer",
    "LMTrainer",
    "LoRATrainer",
]
