"""AsyncDP: bounded-staleness asynchronous data parallelism over hosts.

The last trainer family from the reference (distkeras/trainers.py
DOWNPOUR/AEASGD: async workers committing deltas to a parameter
server), rebuilt on this framework's terms (docs/async.md): each
*host* is a full intra-host ADAG configuration — the same jitted
accumulation step, the same mesh collectives, any zero=/exchange
combination — and hosts exchange parameter deltas through the
:class:`~distkeras_tpu.parallel.async_tier.AsyncPlane` under a
staleness bound τ, an Adasum aggregation tree, and the int8
error-feedback wire.

Hosts here are *simulated* on one process under a seeded virtual-time
clock: every host shares the single compiled step (one XLA program —
the compile budget does not scale with fleet size) but owns its own
``TrainState``, its own contiguous dataset shard, and its own position
in virtual time.  The discrete-event loop is the deterministic
replacement for wall-clock racing: round completions, stalls, barrier
parks, watchdog evictions, joins and leaves are a pure function of
``(seed, schedule)``, so a chaos interleaving replays bit-for-bit —
the property the determinism harness (tests/test_async_tier.py) and
the ``chaos_suite.py --cluster`` async legs assert.  On a real fleet
the same plane logic runs per-host against wall time with
``coord_dir`` heartbeats; nothing in the plane reads the simulation.

Round protocol, per host:

1. ``pull`` center params at version v (a copy — steps donate).
2. run ONE jitted accumulation round on the host's next data window
   (``communication_window`` microbatches, intra-host collectives).
3. ``delta = tv_after - tv_pulled``; ``push`` through the
   ``cluster.push`` chaos probe, int8-EF-encoded, into the tree.
4. re-pull and start the next round — unless the SSP gate blocks it
   (a peer is > τ behind): slow-but-alive laggard -> park under the
   hard-sync barrier; wedged-heartbeat laggard -> the watchdog evicts
   it after ``beat_window`` virtual seconds and the fleet proceeds.

A killed-mid-push host (``fail`` rule on ``cluster.push``) publishes
nothing — its delta is dropped cleanly and the host leaves the
membership, exactly the preemption-immunity contract.
"""

from __future__ import annotations

import heapq

import jax
import numpy as np

from distkeras_tpu import obs
from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.models.adapter import TrainState
from distkeras_tpu.parallel.async_tier import (AsyncConfig, AsyncPlane,
                                                AsyncSchedule, VirtualClock,
                                                copy_tree, delta_of,
                                                make_wire_merge)
from distkeras_tpu.parallel.mesh import per_host_rows
from distkeras_tpu.resilience import chaos
from distkeras_tpu.trainers.distributed import ADAG


class AsyncDP(ADAG):
    """Bounded-staleness async DP: ``hosts`` simulated hosts, staleness
    bound ``tau``, ``async_merge`` ("adasum"/"sum") up a ``fanout``-ary
    aggregation tree, ``async_compress`` (None/"int8") on the wire.

    ``schedule=`` takes an :class:`AsyncSchedule` (stalls, joins,
    leaves); default is the plain seeded heterogeneous-duration
    schedule.  ``coord_dir=`` optionally roots the plane's membership
    epochs + heartbeat files on the cluster substrate.  All intra-host
    ADAG knobs (``zero=``, ``merge_rule=``, ``communication_window=``,
    ...) compose; ``device_data`` does not (the indexed plane has no
    per-host streaming split).

    After ``train()``, ``async_report`` holds the audit trail: virtual
    makespan, per-host rounds, hard-sync/evict/join/leave events, wire
    bytes and the center version history — what the chaos legs and the
    bench rows assert against.
    """

    _supports_device_data = False

    def __init__(self, keras_model, hosts: int = 2, tau: int = 4,
                 async_merge: str = "adasum",
                 async_compress: str | None = "int8",
                 fanout: int = 2, beat_window: float = 3.0,
                 schedule: AsyncSchedule | None = None,
                 async_seed: int = 0, coord_dir: str | None = None,
                 **kw):
        super().__init__(keras_model, **kw)
        if hosts < 1:
            raise ValueError(f"hosts must be >= 1, got {hosts}")
        if self.adapter.ntv_paths:
            raise ValueError(
                "AsyncDP needs a model without non-trainable training "
                "state (BatchNorm running stats, seeded Dropout): "
                "per-host local rounds would diverge it — train such "
                "models with the synchronous trainers")
        self.hosts = int(hosts)
        self.async_config = AsyncConfig(
            tau=tau, merge_rule=async_merge, compress=async_compress,
            fanout=fanout, beat_window=beat_window)
        self.schedule = schedule if schedule is not None \
            else AsyncSchedule(seed=async_seed)
        self.coord_dir = coord_dir
        self.async_report: dict | None = None

    # ------------------------------------------------------------ lint

    def traced_for_analysis(self, dataset: Dataset):
        """The intra-host accumulation step (inherited, the program
        that trains) plus the cross-host wire leg: one compiled
        aggregation wave whose all-gather payload the census audits —
        with ``async_compress="int8"`` the wire dtype is s8."""
        from distkeras_tpu.analysis.ir_lint import TraceSpec

        specs = super().traced_for_analysis(dataset)
        cfg = self.async_config
        n = self.num_workers
        state = jax.eval_shape(self.adapter.init_state)
        stacked = jax.tree.map(
            lambda v: jax.ShapeDtypeStruct((n,) + tuple(v.shape),
                                           np.float32), state.tv)
        pbytes = int(sum(np.prod(v.shape) * v.dtype.itemsize
                         for v in jax.tree.leaves(state.tv)))
        wire = jax.jit(make_wire_merge(self.mesh, cfg))
        label = cfg.merge_rule + ("_int8" if cfg.compress == "int8"
                                  else "")
        specs.append(TraceSpec(
            name=f"asyncdp_wire/{label}", fn=wire, args=(stacked,),
            params_bytes=pbytes))
        return specs

    # ------------------------------------------------------------- fit

    def _host_windows(self, dataset: Dataset):
        """Contiguous per-host data shards, pre-shaped into rounds: the
        epoch's window stream splits into ``hosts`` runs of equal
        length; host ``h`` consumes run ``h`` in order, ``num_epoch``
        times.  A joiner replays run ``host % hosts``."""
        w = self.communication_window
        H = self.exchange.sync_every
        feed_bs = per_host_rows(self.batch_size * self.num_workers)
        wins = []
        for xs, ys in dataset.batches(
                feed_bs, features_col=self.features_col,
                label_col=self.label_col, window=w * H):
            if H > 1:
                xs = xs.reshape((H, w) + xs.shape[1:])
                ys = ys.reshape((H, w) + ys.shape[1:])
            wins.append((xs, ys))
        per_host = len(wins) // self.hosts
        if per_host < 1:
            raise ValueError(
                f"dataset yields {len(wins)} round windows but the "
                f"fleet has {self.hosts} hosts; reduce hosts/batch_size/"
                "communication_window or provide more data")
        shards = [wins[h * per_host:(h + 1) * per_host]
                  for h in range(self.hosts)]
        return shards, feed_bs * w * H, per_host

    def _fit(self, dataset: Dataset):
        cfg = self.async_config
        sched = self.schedule
        state0 = self.adapter.init_state()
        state0, state_sh = self._shard_state(state0)
        batch_sh = self._batch_sharding(
            leading_window=True, leading_sync=self.exchange.sync_every > 1)
        step = self._jit_accum_step(state_sh, batch_sh)
        shards, rows_per_round, per_host = self._host_windows(dataset)

        clock = VirtualClock()
        plane = AsyncPlane(state0.tv, cfg, clock,
                           coord_dir=self.coord_dir)

        # Per-host islands.  tv is pulled from the center; opt_state
        # starts from the shared init (all-zero momenta) — each host's
        # optimizer state stays host-local for the whole run, the
        # DOWNPOUR split (center owns params, workers own momenta).
        opt0, ntv0 = state0.opt_state, state0.ntv
        states: dict[int, TrainState] = {}
        pulled: dict[int, list] = {}
        cursor: dict[int, int] = {}
        quota: dict[int, int] = {}
        shard_of: dict[int, int] = {}
        parked: dict[int, list] = {}
        dead: set[int] = set()
        losses: list[float] = []
        rounds_done: dict[int, int] = {}

        # Discrete events: (time, seq, kind, host).  seq breaks ties
        # deterministically (insertion order).
        events: list[tuple] = []
        seq = 0
        t_work = 0.0  # last productive completion (makespan — an
        #               evicted host's dead event never extends it)

        def push_event(t, kind, host):
            nonlocal seq
            heapq.heappush(events, (t, seq, kind, host))
            seq += 1

        def admit(host, shard_idx, n_rounds):
            tv, _ = plane.join(host)
            states[host] = TrainState(tv=tv, ntv=copy_tree(ntv0),
                                      opt_state=copy_tree(opt0),
                                      step=copy_tree(state0.step))
            pulled[host] = copy_tree(tv)
            cursor[host] = 0
            quota[host] = n_rounds
            shard_of[host] = shard_idx
            rounds_done[host] = 0

        def start_round(host):
            """Schedule the host's next round completion; a stalled
            round wedges its heartbeat writer for the duration."""
            rnd = plane.members[host].round + 1
            if sched.stalled(host, rnd):
                plane.freeze_beats(host)
            push_event(clock.now() + sched.duration(host, rnd),
                       "complete", host)

        def gate(host):
            """SSP gate: start the next round, park under the barrier,
            or retire the host (quota done / scheduled leave)."""
            rnd = plane.members[host].round
            left = sched.leave_after(host)
            if cursor[host] >= quota[host] or (left is not None
                                               and rnd >= left):
                plane.leave(host)
                states.pop(host), pulled.pop(host)
                return
            ok, lag = plane.may_start(host, rnd + 1)
            if ok:
                start_round(host)
            else:
                parked[host] = lag
                push_event(clock.now() + cfg.beat_window, "watchdog",
                           host)

        def unpark():
            """Re-gate every parked host whose laggards caught up or
            left; deterministic order."""
            for host in sorted(parked):
                rnd = plane.members[host].round
                if not [h for h in plane.laggards(rnd + 1) if h != host]:
                    parked.pop(host)
                    gate(host)

        for h in range(self.hosts):
            admit(h, h, self.num_epoch * per_host)
        for h in sorted(states):
            start_round(h)
        joins = list(sched.joins())

        while events:
            t, _, kind, host = heapq.heappop(events)
            clock.advance_to(t)
            while joins and joins[0][0] <= t:
                _, jh = joins.pop(0)
                if jh not in states and jh not in dead:
                    admit(jh, jh % self.hosts, per_host)
                    start_round(jh)
            if kind == "watchdog":
                if host not in parked:
                    continue
                for lag in list(parked[host]):
                    if lag in plane.members and plane.stale(lag):
                        plane.evict(lag, reason="heartbeat_stale")
                        dead.add(lag)
                        states.pop(lag, None), pulled.pop(lag, None)
                        parked.pop(lag, None)
                if host in parked and any(
                        plane.members[l].frozen_at is not None
                        for l in parked[host] if l in plane.members):
                    # A laggard's writer is wedged but not yet past the
                    # window: re-arm the watchdog instead of waiting on
                    # a completion that may never come.
                    push_event(t + cfg.beat_window, "watchdog", host)
                unpark()
                continue
            if host not in states or host in dead:
                continue  # completed after eviction: stale event
            if host in parked:
                continue
            plane.thaw_beats(host)
            shard = shards[shard_of[host]]
            xs, ys = shard[cursor[host] % len(shard)]
            with self.step_timer.phase("h2d"):
                args = (self._global_batch(xs, batch_sh),
                        self._global_batch(ys, batch_sh))
            with self.step_timer.phase("step"):
                state, loss = step(states[host], *args)
            delta = delta_of(state.tv, pulled[host])
            try:
                plane.push(host, delta)
            except chaos.FaultInjected:
                # Host died mid-push: nothing published, delta dropped
                # cleanly; the island disappears and the fleet rolls on.
                plane.evict(host, reason="push_fault")
                dead.add(host)
                states.pop(host, None), pulled.pop(host, None)
                obs.count("async.push_faults", 1, host=host)
                unpark()
                continue
            cursor[host] += 1
            rounds_done[host] = plane.complete(host)
            t_work = t
            losses.append(float(loss))
            tv, _ = plane.pull(host)
            states[host] = state.replace(tv=tv)
            pulled[host] = copy_tree(tv)
            gate(host)
            unpark()

        plane.flush()  # drain any wave a merge fault deferred
        self._require_steps(losses, rows_per_round, len(dataset))
        self._record(losses)
        self.async_report = {
            "makespan": t_work,
            "rounds": dict(sorted(rounds_done.items())),
            "hard_syncs": plane.hard_syncs,
            "evicted": list(plane.evicted),
            "dropped_deltas": plane.dropped_deltas,
            "pushes": plane.pushes,
            "merges": plane.merges,
            "version": plane.version,
            "wire_bytes": plane.wire_bytes,
            "epoch": plane.epoch,
            "members_final": sorted(plane.members),
        }
        obs.gauge("async.makespan", t_work)
        final = TrainState(tv=plane.center, ntv=copy_tree(ntv0),
                           opt_state=copy_tree(opt0),
                           step=jax.numpy.asarray(sum(
                               rounds_done.values(), 0),
                               jax.numpy.int32))
        self._checkpoint(final, plane.version, final=True)
        return final
