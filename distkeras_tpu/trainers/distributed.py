"""Synchronous data-parallel trainers: ADAG and DynSGD.

Reference parity: distkeras/trainers.py::ADAG / DynSGD +
distkeras/workers.py::ADAGWorker / DynSGDWorker +
distkeras/parameter_servers.py (ADAG/DynSGD parameter servers).

Semantic mapping (SURVEY.md §7.4): the reference's workers accumulate
updates for ``communication_window`` batches, then commit the
accumulated delta to a central parameter server and pull fresh weights.
In bulk-synchronous SPMD that cadence is *gradient accumulation*: each
DP replica scans ``window`` microbatches accumulating gradients, the
mean gradient is combined across replicas by the compiler-inserted
all-reduce (the batch is sharded over the mesh ``data`` axis), and one
optimizer update applies it.  The pickle-over-TCP parameter-server hot
path (SURVEY.md §3.2) has no equivalent here — XLA collectives over ICI
do the exchange.

DynSGD's only difference from ADAG was staleness-scaled learning rate
``lr/(tau+1)``; under synchronous execution staleness tau == 0, so
DynSGD degenerates to ADAG exactly (SURVEY.md §7.4).  The class is kept
for API parity.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from distkeras_tpu.parallel.compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.parallel.mesh import (MeshSpec, equal_across_hosts,
                                          make_mesh, per_host_rows,
                                          global_batch as mesh_global_batch)
from distkeras_tpu.parallel.sharding import (ShardingPlan, Zero1Plan,
                                              dp_plan, fsdp_plan,
                                              zero1_plan)
from distkeras_tpu.trainers.base import Trainer


class DistributedTrainer(Trainer):
    """Base for mesh trainers: builds the mesh and sharding plumbing.

    Subclasses that implement the device-resident data plane set
    ``_supports_device_data = True``; everyone else rejects the knob at
    construction.

    ``num_workers`` (reference kwarg) = number of data-parallel replicas
    = size of the mesh's ``data`` axis.  Defaults to all visible
    devices.  A :class:`ShardingPlan` may add tensor parallelism on the
    ``model`` axis on top (something the reference cannot do at all).
    ``fsdp=True`` is shorthand for ``plan=fsdp_plan()``: weights and
    optimizer state scatter over the data axis (ZeRO-3) instead of
    replicating — identical training math, ~num_workers x less
    parameter memory per device.

    ``zero=`` selects a ZeRO sharding stage (docs/zero1.md; identical
    training math at every stage, pure-data meshes only):

    * ``zero=1`` shards only the *weight update*: parameters stay
      replicated (forward/backward untouched), the optimizer state
      scatters over the data axis, and each round's exchange becomes
      reduce-scatter(grads) -> per-replica shard update ->
      all-gather(update), in ~``zero_bucket_mb`` fusion buckets
      (parallel/collectives.py).  Unchanged communication volume,
      ~num_workers x less optimizer memory and update compute per
      device.  ``zero1=True`` is the deprecated alias.
    * ``zero=2`` additionally shards the GRADIENT ACCUMULATOR: each
      microbatch's bucketed reduce-scatter interleaves into the
      accumulation scan, so a replica only ever materializes its 1/n
      gradient shard and the per-round wire drops from ``window``
      all-reduces to ``window`` reduce-scatters + one all-gather.
    * ``zero=3`` additionally shards the PARAMETERS as chunk-major
      ``[n, cols]`` shard views with gather-on-use: the forward
      re-materializes them per fusion bucket just-in-time
      (collectives.gather_bucket) and the update runs entirely on the
      shard views — per-device param+grad+optimizer bytes all drop
      ~num_workers x.  Compare ``fsdp=True`` (the GSPMD
      dimension-sharded spelling, which composes with TP but leaves
      small/indivisible leaves replicated).

    **Gradient-exchange policy** (docs/lowcomm.md, ADAG/DynSGD only):
    ``merge_rule="adasum"`` replaces the mean-reduce with pairwise
    adaptive summation (arXiv 2006.02924); ``sync_every=H`` switches to
    local-SGD — H purely-local rounds per replica, then one
    momentum-aware parameter merge (1/H the collective frequency);
    ``compress="int8"``/``"topk"`` applies an error-feedback codec per
    fusion bucket (~4x fewer gradient wire bytes for int8, pinned in
    scripts/comm_budget.json).  ``compress="int8"`` composes with
    ``zero1=True`` by compressing the reduce-scatter leg.
    ``probe_metrics=True`` adds an in-graph grad-norm probe to the step
    (``probe_history``; zero extra compiled programs — the step is one
    program either way).
    """

    _supports_device_data = False
    _supports_exchange = False

    def __init__(self, keras_model, loss="categorical_crossentropy",
                 worker_optimizer="sgd", learning_rate: float | None = None,
                 batch_size: int = 32, num_epoch: int = 1,
                 num_workers: int | None = None, mesh=None,
                 plan: ShardingPlan | None = None, fsdp: bool = False,
                 zero: int | None = None,
                 zero1: bool = False, zero1_bucket_mb: float | None = None,
                 zero_bucket_mb: float | None = None,
                 device_data: bool = False, merge_rule: str = "mean",
                 sync_every: int = 1, compress=None,
                 topk_frac: float = 0.01, probe_metrics: bool = False,
                 **kw):
        super().__init__(keras_model, loss=loss,
                         worker_optimizer=worker_optimizer,
                         learning_rate=learning_rate, batch_size=batch_size,
                         num_epoch=num_epoch, **kw)
        from distkeras_tpu.trainers.base import normalize_zero_args

        zero, zero1, zero_bucket_mb = normalize_zero_args(
            zero, zero1, zero_bucket_mb, zero1_bucket_mb)
        if device_data and not self._supports_device_data:
            raise ValueError(
                f"device_data=True is not supported by "
                f"{type(self).__name__}; it is implemented for "
                "ADAG/DynSGD, the replica family (AEASGD/EAMSGD/"
                "DOWNPOUR/Averaging/Ensemble), SingleTrainer, and "
                "LMTrainer")
        self.device_data = device_data
        from distkeras_tpu.parallel.exchange import ExchangeConfig

        exchange = ExchangeConfig(
            merge_rule=merge_rule, sync_every=sync_every,
            compress=compress, topk_frac=topk_frac,
            # Under zero1 x int8 the exchange's bucket layout IS the
            # zero1 layout, so the one bucket knob governs both.
            **({} if zero_bucket_mb is None
               else {"bucket_mb": zero_bucket_mb}))
        self.exchange = exchange
        self.probe_metrics = probe_metrics
        self.probe_history: list[dict] = []
        if (not exchange.is_default or probe_metrics) \
                and not self._supports_exchange:
            raise ValueError(
                f"{type(self).__name__} does not support the gradient-"
                "exchange options (merge_rule/sync_every/compress/"
                "probe_metrics); they are implemented for ADAG/DynSGD "
                "(and LMTrainer) — the replica family already has its "
                "own communication cadence")
        if not exchange.is_default:
            if device_data:
                raise ValueError(
                    "merge_rule/sync_every/compress do not compose with "
                    "device_data=True: the exchange layer computes "
                    "per-replica gradients in a shard_map the indexed "
                    "data plane does not route through")
            if fsdp or plan is not None:
                raise ValueError(
                    "merge_rule/sync_every/compress build their own "
                    "placement plan; they do not compose with fsdp=True "
                    "or an explicit plan=")
            if self.adapter.ntv_paths:
                raise ValueError(
                    "gradient-exchange options need a model without "
                    "non-trainable training state (BatchNorm running "
                    "stats, seeded Dropout): per-replica local updates "
                    "would diverge it — train such models with the "
                    "default synchronous exchange")
            if zero and not (zero == 1 and exchange.compress == "int8"
                             and exchange.sync_every == 1):
                raise ValueError(
                    "the ZeRO stages compose with zero=1 + "
                    "compress='int8' only (the chunked codec compresses "
                    "the reduce-scatter leg); adasum, local-SGD, codec "
                    "rules and stages 2/3 replace the exchange the "
                    "sharded update rides")
        if probe_metrics and exchange.sync_every > 1:
            raise ValueError(
                "probe_metrics with sync_every > 1 is not supported: "
                "the local-SGD period has no single per-step global "
                "gradient to probe")
        if probe_metrics and device_data:
            raise ValueError(
                "probe_metrics does not compose with device_data=True "
                "(the indexed data plane's scanned step has no probe "
                "output slot)")
        if sum((fsdp, bool(zero), plan is not None)) > 1:
            raise ValueError(
                "pass only one of plan=, fsdp=True, zero=/zero1=True — "
                "they are alternative placement policies for the same "
                "state")
        if zero_bucket_mb is not None and not zero:
            raise ValueError(
                "zero_bucket_mb/zero1_bucket_mb only apply with a ZeRO "
                "stage (the plan=zero1_plan(...)/zero3_plan(...) "
                "spellings carry their own bucket_mb)")
        if not exchange.is_default:
            from distkeras_tpu.parallel.sharding import ExchangePlan

            self.plan = ExchangePlan(exchange, zero1=zero1)
        else:
            from distkeras_tpu.parallel.sharding import zero3_plan

            self.plan = plan or (fsdp_plan() if fsdp
                                 else zero1_plan(zero_bucket_mb)
                                 if zero == 1
                                 else Zero1Plan(zero_bucket_mb)
                                 if zero == 2
                                 else zero3_plan(zero_bucket_mb)
                                 if zero == 3
                                 else dp_plan())
            # plan=zero1_plan()/zero3_plan() are the explicit spellings
            # of zero=1/zero=3: the plans' sharded layouts only exist
            # if the optimizer/step are wired to produce them.
            if not zero:
                if getattr(self.plan, "zero1", False):
                    zero, zero1 = 1, True
                elif getattr(self.plan, "zero", 0):
                    zero = int(self.plan.zero)
        if mesh is not None:
            self.mesh = mesh
        else:
            devices = jax.devices()
            n = num_workers or len(devices)
            if n > len(devices):
                raise ValueError(
                    f"num_workers={n} exceeds visible devices ({len(devices)}); "
                    "oversubscription is not supported — it would serialize "
                    "on-device anyway")
            self.mesh = make_mesh(MeshSpec(data=n), devices=devices[:n])
        self.num_workers = int(self.mesh.shape["data"])
        if not exchange.is_default:
            for ax, size in self.mesh.shape.items():
                if ax != "data" and int(size) > 1:
                    raise ValueError(
                        "merge_rule/sync_every/compress compose with the "
                        f"data axis only, but the mesh has {ax}="
                        f"{int(size)}")
        self.zero = zero
        self.zero1 = zero1
        self._zero_inner = None
        self._zero_bucket_mb = getattr(self.plan, "bucket_mb", None)
        if zero == 1 and exchange.compress == "int8":
            from distkeras_tpu.parallel.collectives import zero_validate
            from distkeras_tpu.parallel.exchange import exchange_optimizer

            zero_validate(self.mesh, worker_optimizer, stage=zero)
            self.adapter.optimizer = exchange_optimizer(
                self.adapter.optimizer, self.mesh, exchange, zero1=True,
                names=self.adapter.tv_paths)
        elif zero:
            from distkeras_tpu.parallel.collectives import zero1_enable

            # The shared enablement path: zero1_enable runs the
            # construction-time checks for this stage — a known
            # non-elementwise transform (LARS/LAMB trust ratios)
            # raises naming itself instead of silently diverging
            # inside the scattered update — then wraps AFTER the
            # adapter resolved the optimizer: the wrapper is a drop-in
            # GradientTransformation, so init_state and every
            # accum/train step builder pick it up unchanged.  For
            # stages 2/3 only its INIT half is consumed (shard-view
            # state); the zero accum step drives the raw inner update
            # on the scattered views directly (_zero_inner).
            self._zero_inner = self.adapter.optimizer
            self.adapter.optimizer = zero1_enable(
                self._zero_inner, self.mesh, spec=worker_optimizer,
                bucket_mb=self._zero_bucket_mb, stage=zero)
        elif exchange.needs_grad_exchange:
            from distkeras_tpu.parallel.exchange import exchange_optimizer

            self.adapter.optimizer = exchange_optimizer(
                self.adapter.optimizer, self.mesh, exchange,
                names=self.adapter.tv_paths)

    # ------------------------------------------------------------ helpers

    def _zero_view_state(self, state):
        """Stage 3: the persistent ``tv`` is the chunk-major shard-view
        layout (``[n, cols]`` per leaf) — converted ONCE here, before
        placement; the step trains on views end to end."""
        layout = self.adapter.zero_layout(self.num_workers,
                                          self._zero_bucket_mb)
        return state.replace(tv=layout.shard_views(list(state.tv)))

    def _zero_unview_state(self, state):
        """Inverse of :meth:`_zero_view_state` (gathers the scattered
        views): parameter-layout ``tv`` for eval/export."""
        layout = self.adapter.zero_layout(self.num_workers,
                                          self._zero_bucket_mb)
        return state.replace(tv=layout.unview(list(state.tv)))

    def _shard_state(self, state):
        if self.zero >= 3:
            state = self._zero_view_state(state)
        sh = self.plan.state_shardings(self.mesh, state, self.adapter.tv_paths)
        return jax.device_put(state, sh), sh

    def _eval_state_view(self, pytree):
        """Mid-train eval under stage 3 reads the params back out of
        the shard views (a gather per eval round, never per step)."""
        if self.zero >= 3:
            pytree = self._zero_unview_state(pytree)
        return pytree.tv, pytree.ntv

    def _export(self, state):
        if self.zero >= 3:
            state = self._zero_unview_state(state)
        return super()._export(state)

    def _publish_tree(self, state):
        """Live weight push: publish parameter-layout weights (one
        gather per bucket under stage 3, only on publish rounds —
        same cost note as mid-train eval)."""
        tv, ntv = self._eval_state_view(state)
        return {"tv": list(tv), "ntv": list(ntv)}

    def _batch_sharding(self, leading_window: bool,
                        leading_sync: bool = False):
        spec = (P(None, None, "data") if leading_sync
                else P(None, "data") if leading_window else P("data"))
        return NamedSharding(self.mesh, spec)

    def _stacked_local_vag(self):
        """``jax.value_and_grad`` replacement for the gradient-exchange
        configurations: per-replica gradients are computed inside a
        shard_map over ``data`` and returned STACKED (leading replica
        axis, sharded), for :func:`exchange_optimizer` to merge.  The
        loss is pmean'd for reporting.  The LM analogue is
        ``LMTrainer._stacked_local_value_and_grad``."""
        from distkeras_tpu.parallel.compat import shard_map
        mesh = self.mesh

        def value_and_grad(loss, has_aux=True):
            vag = jax.value_and_grad(loss, has_aux=has_aux)

            def wrapped(tv, ntv, x, y):
                def body(tv, ntv, x, y):
                    (l, ntv2), g = vag(tv, ntv, x, y)
                    g = jax.tree.map(lambda v: v[None], g)
                    return (jax.lax.pmean(l, "data"), ntv2), g

                return shard_map(
                    body, mesh=mesh,
                    in_specs=(P(), P(), P("data"), P("data")),
                    out_specs=((P(), P()), P("data")),
                    check_vma=False)(tv, ntv, x, y)

            return wrapped

        return value_and_grad

    # Batch staging shares one definition with LMTrainer
    # (parallel.mesh.global_batch): process-local slab assembly
    # multi-process, device_put under the sharding single-process.
    _global_batch = staticmethod(mesh_global_batch)


class ADAG(DistributedTrainer):
    """Asynchronous Distributed Adaptive Gradients, synchronously.

    ``device_data=True`` stages the dataset in HBM (see
    _fit_device_data).

    Reference parity: distkeras/trainers.py::ADAG (the reference's own
    flagship algorithm, SURVEY.md §3.2).  ``communication_window`` maps
    to gradient-accumulation depth per global step.
    """

    _supports_device_data = True
    _supports_exchange = True

    def __init__(self, keras_model, communication_window: int = 12, **kw):
        super().__init__(keras_model, **kw)
        self.communication_window = communication_window

    def _accum_step_fn(self):
        """The (un-jitted) round step for this exchange configuration:
        local-SGD when ``sync_every > 1``, the stacked-local-gradient
        accumulation step when a merge rule/codec needs per-replica
        gradients, the ZeRO stage-2/3 scattered-accumulator step when
        ``zero >= 2``, the plain accumulation step otherwise."""
        ex = self.exchange
        w = self.communication_window
        if ex.sync_every > 1:
            return self.adapter.make_localsgd_accum_step(
                w, ex.sync_every, self.mesh, ex)
        if ex.needs_grad_exchange:
            return self.adapter.make_accum_train_step(
                w, value_and_grad=self._stacked_local_vag(),
                grad_axis_size=self.num_workers,
                probe=self.probe_metrics)
        if self.zero >= 2:
            return self.adapter.make_zero_accum_step(
                w, self.mesh, self._zero_inner, stage=self.zero,
                bucket_mb=self._zero_bucket_mb,
                probe=self.probe_metrics)
        return self.adapter.make_accum_train_step(
            w, probe=self.probe_metrics)

    def _jit_accum_step(self, state_sh, batch_sh):
        """THE jitted accumulation step of the streaming path — built
        here once so ``_fit`` and :meth:`traced_for_analysis` can never
        drift apart (the IR lint must audit the program that trains)."""
        return jax.jit(
            self._accum_step_fn(),
            in_shardings=(state_sh, batch_sh, batch_sh),
            out_shardings=(state_sh, NamedSharding(self.mesh, P())),
            donate_argnums=0,
        )

    def _jit_indexed_accum_step(self, state_sh, repl, idx_sh):
        """THE jitted step of the single-process device-resident data
        plane — shared by ``_fit_device_data`` and
        :meth:`traced_for_analysis` (same never-drift contract as
        :meth:`_jit_accum_step`).  Under ``zero >= 2`` the indexed
        gather wraps the scattered-accumulator step, so device_data
        and the ZeRO stages compose."""
        accum = (self._accum_step_fn() if self.zero >= 2 else None)
        return jax.jit(
            self.adapter.make_indexed_accum_train_step(
                self.communication_window, accum=accum),
            in_shardings=(state_sh, repl, repl, idx_sh),
            out_shardings=(state_sh, repl),
            donate_argnums=0,
        )

    def traced_for_analysis(self, dataset: Dataset):
        """Trace targets for the IR lint (analysis/ir_lint.py): the
        REAL jitted step this configuration would train with —
        streaming, or the device-resident indexed step under
        ``device_data=True`` (single-process form; the multi-host
        device_data program is a distinct shard_map build not yet
        covered) — plus example argument shapes derived from
        ``dataset`` exactly as the feed loop would shape them.
        Nothing executes and nothing is materialized (state is
        ``eval_shape`` structs) — the lint only traces/lowers."""
        from distkeras_tpu.analysis.ir_lint import TraceSpec

        w = self.communication_window
        H = self.exchange.sync_every
        state = jax.eval_shape(self.adapter.init_state)
        pbytes = int(sum(np.prod(v.shape) * v.dtype.itemsize
                         for v in jax.tree.leaves(state.tv)))
        if self.zero >= 3:
            state = jax.eval_shape(self._zero_view_state, state)
        state_sh = self.plan.state_shardings(self.mesh, state,
                                             self.adapter.tv_paths)
        X = dataset[self.features_col]
        Y = dataset[self.label_col]
        name = type(self).__name__.lower()
        variant = f"zero{self.zero}" if self.zero else "dp"
        if not self.exchange.is_default:
            label = self.exchange.label()
            variant = f"zero1_{label}" if self.zero1 else label
        global_bs = self.batch_size * self.num_workers
        if self.device_data:
            repl = NamedSharding(self.mesh, P())
            idx_sh = NamedSharding(self.mesh, P(None, "data"))
            step = self._jit_indexed_accum_step(state_sh, repl, idx_sh)
            args = (state,
                    jax.ShapeDtypeStruct(X.shape, X.dtype),
                    jax.ShapeDtypeStruct(Y.shape, Y.dtype),
                    jax.ShapeDtypeStruct((w, global_bs), np.int32))
            variant += "_device_data"
        else:
            batch_sh = self._batch_sharding(leading_window=True,
                                            leading_sync=H > 1)
            step = self._jit_accum_step(state_sh, batch_sh)
            lead = (H, w) if H > 1 else (w,)
            args = (state,
                    jax.ShapeDtypeStruct(lead + (global_bs,)
                                         + X.shape[1:], X.dtype),
                    jax.ShapeDtypeStruct(lead + (global_bs,)
                                         + Y.shape[1:], Y.dtype))
        return [TraceSpec(name=f"{name}_{variant}/accum_step", fn=step,
                          args=args, donate_argnums=(0,),
                          params_bytes=pbytes)]

    def _fit(self, dataset: Dataset):
        if self.device_data:
            return self._fit_device_data(dataset)
        w = self.communication_window
        H = self.exchange.sync_every
        state = self.adapter.init_state()
        state, state_sh = self._shard_state(state)
        batch_sh = self._batch_sharding(leading_window=True,
                                        leading_sync=H > 1)

        step = self._jit_accum_step(state_sh, batch_sh)

        # Global batch = num_workers * batch_size rows per microbatch;
        # one jitted call consumes `window` microbatches (x sync_every
        # local rounds under local-SGD).  Each process feeds its share
        # of the global batch from its dataset shard; the balance check
        # keeps hosts from deadlocking the all-reduce
        # (mesh.equal_across_hosts: raise-before-loop, on every host).
        feed_bs = per_host_rows(self.batch_size * self.num_workers)
        equal_across_hosts(len(dataset) // (feed_bs * w * H),
                           f"step counts ({feed_bs * w * H}-row windows)")

        def stream():
            for _ in range(self.num_epoch):
                for xs, ys in dataset.batches(
                        feed_bs, features_col=self.features_col,
                        label_col=self.label_col, window=w * H):
                    if H > 1:
                        # [H*w, feed, ...] -> [H, w, feed, ...]: the
                        # first w microbatches are local round 1 — the
                        # same rows, in the same order, the synchronous
                        # path would consume.
                        xs = xs.reshape((H, w) + xs.shape[1:])
                        ys = ys.reshape((H, w) + ys.shape[1:])
                    with self.step_timer.phase("h2d"):
                        args = (self._global_batch(xs, batch_sh),
                                self._global_batch(ys, batch_sh))
                    yield args

        return self._run_rounds(state, step, stream(), feed_bs * w * H,
                                dataset)

    def _run_rounds(self, state, step, rounds, rows_per_round, dataset):
        """ONE round-loop driver for the streaming and device-resident
        paths: resume skipping, loss/checkpoint/eval bookkeeping, and
        the end-of-run guards must not drift between them."""
        losses, probes, rnd = [], [], 0
        state, start = self._restore_or(state)
        for args in rounds:
            rnd += 1
            if rnd <= start:
                continue
            with self.step_timer.phase("step"):
                state, out = step(state, *args)
            if self.probe_metrics:
                loss, aux = out
                probes.append(aux)
            else:
                loss = out
            losses.append(loss)
            self._checkpoint(state, rnd)
            self._eval_hook(state, rnd)
        if start and not losses:
            return state
        self._require_steps(losses, rows_per_round, len(dataset))
        self._record(losses)
        self._record_probes(probes, state)
        self._checkpoint(state, rnd, final=True)
        return state

    def _record_probes(self, probes, state) -> None:
        """Retire the in-graph probe scalars (one device->host sync at
        END of run, never per step) and the exchange layer's residual
        diagnostic into obs."""
        if probes:
            self.probe_history = [
                {k: float(v) for k, v in p.items()} for p in probes]
            from distkeras_tpu import obs

            last = self.probe_history[-1]
            for k, v in last.items():
                obs.gauge(f"train.{k}", v, trainer=type(self).__name__)
        if self.exchange.compress is not None:
            from distkeras_tpu import obs
            from distkeras_tpu.parallel.exchange import residual_norm_of

            rn = residual_norm_of(state.opt_state)
            if rn is not None:
                obs.gauge("exchange.residual_norm", rn)
                self.residual_norm = rn


    def _fit_device_data(self, dataset: Dataset):
        """Device-resident data plane for the distributed flagship.

        The dataset columns are staged in HBM ONCE, replicated on the
        mesh; each round ships only a [window, global_batch] int32
        index block, sharded over the ``data`` axis, and every replica
        gathers its own rows on device — the distributed form of
        SingleTrainer's ``device_data`` (docs/perf_input_pipeline.md:
        the streaming path is capped by the host link, 320k vs ~10k
        samples/s on this relay).  Training math is EXACTLY the
        streaming path's (same accum step fed the same rows in the same
        order — exactness-tested).

        Multi-process meshes take :meth:`_fit_device_data_multihost`:
        per-host shard-local staging (each host's rows live only on its
        own devices) with replica-local gathers under shard_map — no
        row is ever duplicated or shipped cross-host.
        """
        if jax.process_count() > 1:
            return self._fit_device_data_multihost(dataset)
        w = self.communication_window
        state = self.adapter.init_state()
        state, state_sh = self._shard_state(state)
        repl = NamedSharding(self.mesh, P())
        idx_sh = NamedSharding(self.mesh, P(None, "data"))

        step = self._jit_indexed_accum_step(state_sh, repl, idx_sh)
        X = jax.device_put(dataset[self.features_col], repl)
        Y = jax.device_put(dataset[self.label_col], repl)
        global_bs = self.batch_size * self.num_workers
        rows = global_bs * w
        n = len(dataset)

        def index_blocks():
            for _ in range(self.num_epoch):
                for i in range(0, n - (n % rows), rows):
                    idx = np.arange(i, i + rows, dtype=np.int32).reshape(
                        w, global_bs)
                    with self.step_timer.phase("h2d"):
                        idx_dev = jax.device_put(idx, idx_sh)
                    yield (X, Y, idx_dev)

        return self._run_rounds(state, step, index_blocks(), rows,
                                dataset)

    def _fit_device_data_multihost(self, dataset: Dataset):
        """Device-resident data plane across hosts (round-3 verdict:
        the single-process-only ValueError cut against the framework's
        distributed-first identity).

        Each host stages ITS ``Dataset.shard`` in HBM once, laid out so
        every replica's consumption stream is CONTIGUOUS in its own
        shard of the global array: the host's usable rows, viewed as
        ``[chunks, local_replicas, batch]``, are transposed to
        ``[local_replicas, chunks * batch]`` before staging under
        ``P("data")`` — device ``l`` of this host then holds exactly
        the rows streaming would feed it, in consumption order.  Per
        round only one replicated ``[window, batch]`` index block
        crosses the link, and a ``shard_map`` gathers each replica's
        microbatch rows from its LOCAL block (a sharded-``X`` gather
        under plain jit would allgather the dataset every step).  The
        gathered global batch re-enters the same accum step as the
        streaming path with the same sharding, so the training math
        and data order are EXACTLY the streaming multi-process run's
        (replica ``(h, l)`` sees host h's rows
        ``chunk * feed + l * batch + k`` either way) — parity-tested in
        tests/test_deploy.py.
        """
        w = self.communication_window
        pcount = jax.process_count()
        feed_bs = per_host_rows(self.batch_size * self.num_workers)
        n_local_dev = self.num_workers // pcount
        bs = self.batch_size
        n = len(dataset)
        usable = equal_across_hosts(
            n - n % (feed_bs * w),
            f"usable row counts ({feed_bs * w}-row windows)")
        if usable == 0:
            raise ValueError(
                f"dataset shard has {n} rows but one training step needs "
                f"{feed_bs * w} per host; reduce "
                "batch_size/communication_window/num_workers or provide "
                "more data")
        chunks = usable // feed_bs             # multiple of w

        def stream_layout(col):
            # [chunks, L, bs, ...] -> [L, chunks*bs, ...]: device l's
            # contiguous block = its consumption stream.
            a = np.asarray(col[:usable])
            a = a.reshape((chunks, n_local_dev, bs) + a.shape[1:])
            a = np.moveaxis(a, 1, 0)
            return np.ascontiguousarray(
                a.reshape((usable,) + a.shape[3:]))

        data_sh = NamedSharding(self.mesh, P("data"))
        rep = NamedSharding(self.mesh, P())
        X = jax.make_array_from_process_local_data(
            data_sh, stream_layout(dataset[self.features_col]))
        Y = jax.make_array_from_process_local_data(
            data_sh, stream_layout(dataset[self.label_col]))

        state = self.adapter.init_state()
        state, state_sh = self._shard_state(state)
        accum = (self._accum_step_fn() if self.zero >= 2
                 else self.adapter.make_accum_train_step(w))
        mesh = self.mesh

        def local_gather(Xb, Yb, idx):
            # Xb [chunks*bs, ...]: THIS replica's stream; idx [w, bs]
            # replicated block-local offsets (identical per replica).
            shape = lambda a: (w, bs) + a.shape[1:]
            return (jnp.take(Xb, idx.reshape(-1), axis=0).reshape(
                        shape(Xb)),
                    jnp.take(Yb, idx.reshape(-1), axis=0).reshape(
                        shape(Yb)))

        gather = shard_map(
            local_gather, mesh=mesh,
            in_specs=(P("data"), P("data"), P()),
            out_specs=(P(None, "data"), P(None, "data")),
            check_vma=False)

        def step_fn(state, X, Y, idx):
            xs, ys = gather(X, Y, idx)
            return accum(state, xs, ys)

        step = jax.jit(
            step_fn,
            in_shardings=(state_sh, data_sh, data_sh, rep),
            out_shardings=(state_sh, NamedSharding(self.mesh, P())),
            donate_argnums=0,
        )

        def index_blocks():
            for _ in range(self.num_epoch):
                for r in range(chunks // w):
                    idx = np.arange(r * w * bs, (r + 1) * w * bs,
                                    dtype=np.int32).reshape(w, bs)
                    # device_put cannot target non-addressable devices;
                    # every host holds the identical block, so assemble
                    # the replicated global array from the local copy.
                    with self.step_timer.phase("h2d"):
                        idx_dev = jax.make_array_from_process_local_data(
                            rep, idx, idx.shape)
                    yield (X, Y, idx_dev)

        return self._run_rounds(state, step, index_blocks(), feed_bs * w,
                                dataset)


class DynSGD(ADAG):
    """Dynamic SGD.  Reference parity: distkeras/trainers.py::DynSGD.

    The reference scales each commit's learning rate by 1/(tau+1) where
    tau is the update staleness (DynSGDParameterServer).  Synchronous
    execution has tau == 0 identically, so DynSGD == ADAG here; kept as
    a distinct class for API parity (SURVEY.md §7.4).
    """
