"""Job deployment: describe and launch multi-host TPU training jobs.

Reference parity: distkeras/job_deployment.py::Job — the reference's
experimental "punchcard" job submission, which ships a job spec to a
Spark cluster over ssh.  The TPU-native equivalent is process-per-host
SPMD: the *same* Python program starts on every host of a pod slice,
calls ``jax.distributed.initialize`` (host 0 is the coordinator), and
every host then sees the global device mesh.  There is no driver/worker
asymmetry to orchestrate and no closure shipping — deployment reduces
to "run this command on every host", which is exactly what this module
generates.

:class:`Job` is a declarative spec; ``command_for(host)`` renders the
per-host launch command (the form consumed by ``gcloud compute tpus
tpu-vm ssh --worker=all --command=...`` or any ssh fan-out), and
``run_local()`` executes the single-host case in-process for dev runs.
No ssh client is embedded — shelling out is deliberately left to the
operator's tooling (the reference's paramiko dependency was its least
portable part).
"""

from __future__ import annotations

import dataclasses
import os
import shlex
import subprocess
import sys


@dataclasses.dataclass
class Job:
    """A multi-host SPMD training job.

    ``script`` runs identically on every host; per-host identity comes
    from env vars consumed by
    distkeras_tpu.parallel.mesh.initialize_multihost.
    """

    script: str
    num_hosts: int = 1
    coordinator: str = "localhost:8476"
    env: dict = dataclasses.field(default_factory=dict)
    args: tuple = ()
    # Remote hosts' interpreter — NOT sys.executable, whose path is only
    # meaningful on the machine rendering the commands.
    interpreter: str = "python3"

    def env_for(self, host_id: int) -> dict:
        if not (0 <= host_id < self.num_hosts):
            raise ValueError(f"host_id {host_id} outside 0..{self.num_hosts - 1}")
        return {
            **{k: str(v) for k, v in self.env.items()},
            "DKT_COORDINATOR": self.coordinator,
            "DKT_NUM_HOSTS": str(self.num_hosts),
            "DKT_HOST_ID": str(host_id),
        }

    def command_for(self, host_id: int) -> str:
        """Shell command launching this job on ``host_id``."""
        env = " ".join(f"{k}={shlex.quote(v)}"
                       for k, v in sorted(self.env_for(host_id).items()))
        argv = " ".join(shlex.quote(a) for a in
                        (self.script, *map(str, self.args)))
        return f"env {env} {shlex.quote(self.interpreter)} {argv}"

    def command_lines(self) -> list[str]:
        """One launch command per host (feed to your ssh fan-out)."""
        return [self.command_for(h) for h in range(self.num_hosts)]

    def run_local(self, check: bool = True,
                  timeout: float | None = None) -> subprocess.CompletedProcess:
        """Run the single-host case as a subprocess (dev workflow).

        ``timeout``: seconds before the child is killed and
        ``TimeoutError`` raised (None = wait forever).  A nonzero exit
        propagates as ``RuntimeError`` naming the script and returncode
        (``check=False`` restores the inspect-the-CompletedProcess
        escape hatch) — a dev-loop job that failed must never read as
        success.
        """
        if self.num_hosts != 1:
            raise ValueError(
                f"run_local is for num_hosts=1 jobs; this job has "
                f"{self.num_hosts} hosts — use command_lines() with your "
                "cluster's ssh fan-out")
        try:
            proc = subprocess.run(
                [sys.executable, self.script, *map(str, self.args)],
                env={**os.environ, **self.env_for(0)}, timeout=timeout)
        except subprocess.TimeoutExpired as e:
            raise TimeoutError(
                f"job {self.script!r} did not finish within "
                f"{timeout}s (child killed)") from e
        if check and proc.returncode != 0:
            raise RuntimeError(
                f"job {self.script!r} exited with returncode "
                f"{proc.returncode}")
        return proc


def init_from_env() -> None:
    """Join the multi-host runtime using the env vars a :class:`Job` sets.

    Call once at the top of a job script.  No-op when the job is
    single-host (the common dev case), so scripts run unchanged locally
    and on pods.
    """
    from distkeras_tpu.parallel.mesh import initialize_multihost

    num = int(os.environ.get("DKT_NUM_HOSTS", "1"))
    if num > 1:
        initialize_multihost(
            coordinator_address=os.environ["DKT_COORDINATOR"],
            num_processes=num,
            process_id=int(os.environ["DKT_HOST_ID"]),
        )
