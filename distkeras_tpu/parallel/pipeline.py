"""Pipeline parallelism: GPipe-style microbatch schedule on the mesh.

Absent from the reference (SURVEY.md §2 census: no PP), present here
because stage-partitioned models are part of the first-class parallelism
surface.  The construction is the idiomatic TPU one: no runtime
scheduler process (the reference would have used its socket fabric) —
the schedule is *compiled into the program* as a `lax.scan` over clock
ticks inside a `shard_map` that is manual over only the ``pipeline``
axis.  Each tick every stage applies itself to its current microbatch
and `ppermute`s the activation to its right neighbour over ICI; after
``microbatches + n_stages - 1`` ticks the last stage has produced every
microbatch (the classic GPipe bubble).  Because only ``pipeline`` is
manual, data/tensor/expert sharding inside the stage function stays
XLA-automatic, so PP composes with DP/TP/EP.

Differentiable end-to-end: scan + ppermute transpose cleanly, so
`jax.grad` through a pipelined forward runs the reverse schedule.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from distkeras_tpu.parallel.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def make_pipeline(stage_fn: Callable, mesh: Mesh, microbatches: int,
                  axis_name: str = "pipeline", x_spec: P = P(),
                  extras_spec: P | None = None):
    """Build ``f(stage_params, x) -> (y, aux)`` running ``stage_fn`` as a
    pipeline.

    ``stage_params``: pytree whose leaves have a leading [n_stages] axis
    (stage i consumes slice i).  ``x``: [B, ...] global batch, split
    into ``microbatches`` equal microbatches.
    ``stage_fn(params, u) -> (u_out, aux)`` must be shape-preserving on
    ``u`` ([mb, ...] -> [mb, ...]) and return a scalar auxiliary loss
    (0 when it has none); stages that change activation shape belong
    outside the pipeline (embed / head), matching how GPipe slices a
    residual trunk.

    ``extras_spec`` non-None adds a third input: per-microbatch
    side data ``extras`` with leaves ``[microbatches, mb, ...]``
    (e.g. packed-sequence segment ids).  It is NOT piped stage to
    stage: every stage holds the whole (small) array and indexes the
    microbatch it is currently processing (tick t, stage i works
    microbatch t - i), receiving it as ``stage_fn(params, u, extra)``.
    Bubble ticks see a clamped index — garbage in, garbage out, masked
    like the activations.  The spec names any extra manual axes the
    trailing dims shard over (e.g. ``P(None, None, 'seq')``).

    ``aux`` is the per-stage aux summed over stages, averaged over
    microbatches — each microbatch computes its own full-forward aux, so
    the mean keeps it on the same scale as an un-pipelined forward.
    Bubble ticks (a stage holding no real microbatch) are masked out of
    the accumulation.

    ``x_spec`` extends the manual axis set: a PartitionSpec over ``x``'s
    dims naming further mesh axes (e.g. ``P(None, 'seq')`` for sequence
    parallelism) makes the body manual over those too, with ``x``
    entering as the named shard.  ``stage_fn`` then runs with those axes
    manual in context, so it may call collective bodies (ring attention)
    directly — nesting a second shard_map inside the pipeline does not
    transpose under AD, composing manual axes in one shard_map does.
    Every other mesh axis (data, model, expert) stays XLA-automatic.
    """
    n_stages = int(mesh.shape[axis_name])
    extra_axes = {a for dim in x_spec for a in (
        dim if isinstance(dim, tuple) else (dim,)) if a is not None}
    if axis_name in extra_axes:
        raise ValueError(f"x_spec {x_spec} must not name the pipeline "
                         f"axis {axis_name!r}")

    def run(stage_params, x, *maybe_extras):
        for leaf in jax.tree.leaves(stage_params):
            if leaf.shape[0] != 1:
                raise ValueError(
                    f"stage_params leading axis must equal n_stages="
                    f"{n_stages} (got a shard of {leaf.shape[0]} — stack "
                    "exactly one param slice per pipeline stage)")
        local = jax.tree.map(lambda a: a[0], stage_params)
        idx = jax.lax.axis_index(axis_name)
        b = x.shape[0]
        if b % microbatches:
            raise ValueError(f"batch {b} not divisible into {microbatches} "
                             "microbatches")
        mb = b // microbatches
        x_mb = x.reshape(microbatches, mb, *x.shape[1:])
        ticks = microbatches + n_stages - 1
        perm = [(i, i + 1) for i in range(n_stages - 1)]

        def tick(carry, t):
            recv, outputs, aux_acc = carry
            t_in = jnp.clip(t, 0, microbatches - 1)
            inp = jnp.where(idx == 0, x_mb[t_in], recv)
            if maybe_extras:
                cur = jnp.clip(t - idx, 0, microbatches - 1)
                extra = jax.tree.map(lambda a: a[cur], maybe_extras[0])
                out, aux = stage_fn(local, inp, extra)
            else:
                out, aux = stage_fn(local, inp)
            # Stage `idx` holds real microbatch t - idx at tick t; other
            # ticks are bubble garbage and must not pollute the aux sum.
            valid = (t >= idx) & (t - idx < microbatches)
            aux_acc = aux_acc + jnp.where(valid, aux.astype(jnp.float32), 0.0)
            recv_next = jax.lax.ppermute(out, axis_name, perm)
            # Stage n-1 finishes microbatch t-(n-1) at tick t.
            mb_i = t - (n_stages - 1)
            upd = jax.lax.dynamic_update_index_in_dim(
                outputs, out, jnp.maximum(mb_i, 0), 0)
            outputs = jnp.where((idx == n_stages - 1) & (mb_i >= 0),
                                upd, outputs)
            return (recv_next, outputs, aux_acc), None

        zero_act = jnp.zeros((mb, *x.shape[1:]), x.dtype)
        zero_out = jnp.zeros((microbatches, mb, *x.shape[1:]), x.dtype)
        (_, outputs, aux_acc), _ = jax.lax.scan(
            tick, (zero_act, zero_out, jnp.zeros((), jnp.float32)),
            jnp.arange(ticks))
        # aux differs across the extra manual axes (e.g. each seq shard
        # routes its own tokens through MoE), but its out_spec only
        # names the pipeline axis — reduce explicitly so the claimed
        # replication is real (check_vma=False would not catch it).
        for ax in sorted(extra_axes):
            aux_acc = jax.lax.pmean(aux_acc, ax)
        # Leading stage axis: only the last stage's slice is the result;
        # aux contributions live on every stage.
        return outputs.reshape(b, *x.shape[1:])[None], aux_acc[None]

    if extras_spec is not None:
        extras_axes = {a for dim in extras_spec for a in (
            dim if isinstance(dim, tuple) else (dim,)) if a is not None}
        if axis_name in extras_axes:
            raise ValueError(
                f"extras_spec {extras_spec} must not name the pipeline "
                f"axis {axis_name!r} (extras are not piped stage to "
                "stage; every stage holds the whole array)")
        if not extras_axes <= extra_axes:
            # out_specs claims y replicated over exactly x_spec's axes;
            # an extras-only manual axis would make each shard compute
            # a DIFFERENT y while check_vma=False suppresses the check
            # — reject instead of returning silently wrong outputs.
            raise ValueError(
                f"extras_spec {extras_spec} names axes "
                f"{sorted(extras_axes - extra_axes)} that x_spec "
                f"{x_spec} does not — activations must be manual over "
                "every axis the extras shard over")
    in_specs = (P(axis_name), x_spec) + (
        (extras_spec,) if extras_spec is not None else ())
    f = shard_map(run, mesh=mesh, axis_names={axis_name} | extra_axes,
                  in_specs=in_specs,
                  out_specs=(P(axis_name, *x_spec), P(axis_name)),
                  check_vma=False)

    def apply(stage_params, x, extras=None):
        if (extras is not None) != (extras_spec is not None):
            raise ValueError(
                "extras and extras_spec must be provided together "
                f"(extras_spec={'set' if extras_spec is not None else None},"
                f" extras={'given' if extras is not None else None})")
        args = (stage_params, x) + ((extras,) if extras is not None else ())
        ys, aux = f(*args)
        return ys[-1], aux.sum() / microbatches

    return apply
