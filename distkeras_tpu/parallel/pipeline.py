"""Pipeline parallelism: GPipe-style microbatch schedule on the mesh.

Absent from the reference (SURVEY.md §2 census: no PP), present here
because stage-partitioned models are part of the first-class parallelism
surface.  The construction is the idiomatic TPU one: no runtime
scheduler process (the reference would have used its socket fabric) —
the schedule is *compiled into the program* as a `lax.scan` over clock
ticks inside a `shard_map` that is manual over only the ``pipeline``
axis.  Each tick every stage applies itself to its current microbatch
and `ppermute`s the activation to its right neighbour over ICI; after
``microbatches + n_stages - 1`` ticks the last stage has produced every
microbatch (the classic GPipe bubble).  Because only ``pipeline`` is
manual, data/tensor/expert sharding inside the stage function stays
XLA-automatic, so PP composes with DP/TP/EP.

Differentiable end-to-end: scan + ppermute transpose cleanly, so
`jax.grad` through a pipelined forward runs the reverse schedule.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def make_pipeline(stage_fn: Callable, mesh: Mesh, microbatches: int,
                  axis_name: str = "pipeline"):
    """Build ``f(stage_params, x) -> y`` running ``stage_fn`` as a pipeline.

    ``stage_params``: pytree whose leaves have a leading [n_stages] axis
    (stage i consumes slice i).  ``x``: [B, ...] global batch, split
    into ``microbatches`` equal microbatches.  ``stage_fn(params, u)``
    must be shape-preserving on ``u`` ([mb, ...] -> [mb, ...]); stages
    that change activation shape belong outside the pipeline (embed /
    head), matching how GPipe slices a residual trunk.
    """
    n_stages = int(mesh.shape[axis_name])

    def run(stage_params, x):
        for leaf in jax.tree.leaves(stage_params):
            if leaf.shape[0] != 1:
                raise ValueError(
                    f"stage_params leading axis must equal n_stages="
                    f"{n_stages} (got a shard of {leaf.shape[0]} — stack "
                    "exactly one param slice per pipeline stage)")
        local = jax.tree.map(lambda a: a[0], stage_params)
        idx = jax.lax.axis_index(axis_name)
        b = x.shape[0]
        if b % microbatches:
            raise ValueError(f"batch {b} not divisible into {microbatches} "
                             "microbatches")
        mb = b // microbatches
        x_mb = x.reshape(microbatches, mb, *x.shape[1:])
        ticks = microbatches + n_stages - 1
        perm = [(i, i + 1) for i in range(n_stages - 1)]

        def tick(carry, t):
            recv, outputs = carry
            t_in = jnp.clip(t, 0, microbatches - 1)
            inp = jnp.where(idx == 0, x_mb[t_in], recv)
            out = stage_fn(local, inp)
            recv_next = jax.lax.ppermute(out, axis_name, perm)
            # Stage n-1 finishes microbatch t-(n-1) at tick t.
            mb_i = t - (n_stages - 1)
            upd = jax.lax.dynamic_update_index_in_dim(
                outputs, out, jnp.maximum(mb_i, 0), 0)
            outputs = jnp.where((idx == n_stages - 1) & (mb_i >= 0),
                                upd, outputs)
            return (recv_next, outputs), None

        zero_act = jnp.zeros((mb, *x.shape[1:]), x.dtype)
        zero_out = jnp.zeros((microbatches, mb, *x.shape[1:]), x.dtype)
        (_, outputs), _ = jax.lax.scan(
            tick, (zero_act, zero_out), jnp.arange(ticks))
        # Leading stage axis: only the last stage's slice is the result.
        return outputs.reshape(b, *x.shape[1:])[None]

    f = shard_map(run, mesh=mesh, axis_names={axis_name},
                  in_specs=(P(axis_name), P()), out_specs=P(axis_name),
                  check_vma=False)

    def apply(stage_params, x):
        return f(stage_params, x)[-1]

    return apply
