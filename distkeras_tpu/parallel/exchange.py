"""Pluggable gradient-exchange layer: Adasum, local-SGD, EF codecs.

The data-parallel trainers' default exchange is the compiler-inserted
mean all-reduce (or ZeRO-1's RS+AG, ``parallel/collectives.py``).  This
module makes the exchange a *policy*, reviving the source paper's
low-communication identity (DOWNPOUR/AEASGD's "talk less, learn more")
at modern scale:

* **Adasum merge** ("Scaling Distributed Training with Adaptive
  Summation", arXiv 2006.02924): replicas' gradients combine pairwise
  with adaptive weights ``1 - <g_i, g_j> / (2 |g_i|^2)`` instead of a
  plain mean, so nearly-parallel gradients average (identical replicas
  reproduce mean-reduce exactly) while orthogonal ones *sum* — the
  property that tolerates much larger effective batches.
* **Error-feedback compression codecs** (motivated by the bandwidth
  analysis in "Scaling Distributed ML with In-Network Aggregation",
  arXiv 1903.06701): per fusion bucket, the int8 codec quantizes each
  replica's contribution (plus the carried residual), moves an int8
  wire payload through a chunked two-phase reduce (all-to-all partial
  sums, then an all-gather of the re-quantized chunks — the compressed
  spelling of reduce-scatter + all-gather), and dequantizes; the
  residual ``x - decode(encode(x))`` carries to the next step, which is
  what keeps convergence honest.  Wire bytes drop ~4x vs f32 (pinned
  exactly by the collective census in ``scripts/comm_budget.json``).
  The top-k codec keeps the ``topk_frac`` largest-magnitude entries per
  bucket instead.  ``zero1=True`` composes by compressing the
  reduce-scatter leg and leaving the all-gather of the (already
  sharded-computed) update in full precision.
* **Local-SGD / periodic sync** (``sync_every=H``): H purely-local
  optimizer steps per replica, then ONE cross-replica parameter merge
  (momentum buffers averaged too — the momentum-aware variant), cutting
  collective frequency to 1/H.  The step builders live with the trainer
  families (``models/adapter.py``, ``trainers/lm.py``); the merge rules
  here are shared.

All rules operate on **stacked local gradients**: the trainers compute
per-replica gradients inside a ``shard_map`` over the ``data`` axis and
return them with a leading replica axis (global ``[n, *leaf]``, sharded
``P("data")``), so the exchange sees the pre-reduction contributions the
compiler path never materializes.  Bucketing reuses
:class:`~distkeras_tpu.parallel.collectives.Zero1Layout` — the same
~``bucket_mb`` dtype-grouped fusion buckets ZeRO-1 overlaps.

See docs/lowcomm.md for merge-rule semantics, the codec contract, and
when local-SGD is safe.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distkeras_tpu import obs
from distkeras_tpu.parallel.collectives import (DEFAULT_BUCKET_MB,
                                                 Zero1Layout, all_gather,
                                                 zero1_shard_shapes)
from distkeras_tpu.parallel.compat import shard_map

_MERGE_RULES = ("mean", "adasum")
_CODECS = (None, "int8", "topk")
# Smallest positive normal f32: the zero-norm/zero-scale guard.
_EPS = np.float32(np.finfo(np.float32).tiny)


@dataclasses.dataclass(frozen=True)
class ExchangeConfig:
    """One gradient-exchange policy (validated at construction).

    ``merge_rule``: "mean" (the baseline semantics) or "adasum".
    ``sync_every``: local-SGD period H (1 = sync every step).
    ``compress``: None, "int8" (error-feedback symmetric int8), "topk"
    (error-feedback magnitude top-k, ``topk_frac`` of each bucket) —
    or an ordered sequence of ``(regex, codec)`` RULES resolved per
    parameter leaf by the shared rule engine (``parallel/rules.py``,
    first-match-wins over flattened key paths / Keras variable paths;
    an unmatched leaf raises naming it).  Under rules the fusion
    buckets group by (dtype, codec) so every bucket is
    codec-homogeneous — e.g. ``[("emb", "topk"), (".*", "int8")]``
    sends embedding gradients top-k and dense gradients int8, and the
    compiled census pins each bucket's wire dtype separately.
    ``bucket_mb`` sizes the fusion buckets (same knob as ZeRO-1).

    Composition limits (raise here, not deep in a trace):
    ``compress`` requires ``merge_rule="mean"`` (the codecs implement a
    compressed *sum*; Adasum needs the uncompressed stacks) and
    ``sync_every=1`` (local-SGD exchanges parameters, not gradients).
    Codec RULES do not compose with the ZeRO stages (only the uniform
    ``"int8"`` codec has a chunked compressed-reduce-scatter form).
    """

    merge_rule: str = "mean"
    sync_every: int = 1
    compress: str | tuple | None = None
    topk_frac: float = 0.01
    bucket_mb: float = DEFAULT_BUCKET_MB

    def __post_init__(self):
        if self.merge_rule not in _MERGE_RULES:
            raise ValueError(
                f"merge_rule must be one of {_MERGE_RULES}, got "
                f"{self.merge_rule!r}")
        if isinstance(self.compress, (list, tuple)):
            import re

            rules = []
            for entry in self.compress:
                try:
                    pat, codec = entry
                except (TypeError, ValueError):
                    raise ValueError(
                        "compress rules must be (pattern, codec) "
                        f"pairs, got {entry!r}")
                if codec not in ("int8", "topk"):
                    raise ValueError(
                        f"compress rule {pat!r} names codec {codec!r}; "
                        "known codecs: 'int8', 'topk'")
                re.compile(pat)  # typos raise here, not mid-trace
                rules.append((str(pat), str(codec)))
            if not rules:
                raise ValueError(
                    "compress=[] is ambiguous: pass None for no codec "
                    "or at least one (pattern, codec) rule")
            object.__setattr__(self, "compress", tuple(rules))
        elif self.compress not in _CODECS:
            raise ValueError(
                f"compress must be one of {_CODECS} or a sequence of "
                f"(regex, codec) rules, got {self.compress!r}")
        if self.sync_every < 1:
            raise ValueError(
                f"sync_every must be >= 1, got {self.sync_every}")
        if not 0.0 < self.topk_frac <= 1.0:
            raise ValueError(
                f"topk_frac must be in (0, 1], got {self.topk_frac}")
        if self.compress is not None and self.merge_rule != "mean":
            raise ValueError(
                "compress composes with merge_rule='mean' only: the "
                "codecs implement a compressed sum, while adasum needs "
                "every replica's uncompressed contribution")
        if self.compress is not None and self.sync_every > 1:
            raise ValueError(
                "compress with sync_every > 1 is not supported: "
                "local-SGD exchanges parameters once per period, so "
                "there is no per-step gradient wire to compress")
        if self.sync_every > 1 and self.merge_rule == "adasum":
            # Allowed: adasum applies to the parameter DELTAS at sync.
            pass

    @property
    def is_default(self) -> bool:
        """True when this config means "the compiler-inserted mean
        exchange" — the trainers skip the whole layer then."""
        return (self.merge_rule == "mean" and self.sync_every == 1
                and self.compress is None)

    @property
    def needs_grad_exchange(self) -> bool:
        """Per-step gradient merging (vs local-SGD's parameter sync)."""
        return not self.is_default and self.sync_every == 1

    @property
    def codec_rules(self) -> tuple | None:
        """The (pattern, codec) rules when ``compress`` is rule-based,
        else None."""
        return self.compress if isinstance(self.compress, tuple) else None

    def label(self) -> str:
        parts = []
        if self.merge_rule != "mean":
            parts.append(self.merge_rule)
        if self.sync_every > 1:
            parts.append(f"localsgd{self.sync_every}")
        if self.codec_rules is not None:
            parts.append("rulesef")
        elif self.compress:
            parts.append(f"{self.compress}ef")
        return "_".join(parts) or "mean"


@flax.struct.dataclass
class ExchangeState:
    """Error-feedback carry of one exchange policy (a pytree; rides
    inside the optimizer state so checkpointing and the Supervisor's
    bit-for-bit resume cover it with zero extra machinery).

    ``e1``: per-bucket phase-1 residuals — each replica's quantization
    error on its local contribution; global ``[n, n, C_b]`` sharded
    ``P("data", None, None)`` (leading axis = replica).  ``e2``:
    per-bucket phase-2 residuals of the re-quantized reduced chunk;
    global ``[n, C_b]`` sharded ``P("data", None)``.  Both empty
    without a codec.  ``residual_norm``: replicated scalar, the global
    L2 norm of all residuals after the last update — the EF diagnostic
    the obs layer reads at end of run.
    """

    e1: Any
    e2: Any
    residual_norm: Any


# ------------------------------------------------------------- adasum


def _adasum_pair(a, b):
    """Pairwise adaptive sum of two same-shape f32 vectors.

    ``(1 - <a,b>/(2|a|^2)) a + (1 - <a,b>/(2|b|^2)) b`` — the mean for
    parallel inputs, the plain sum for orthogonal ones.  Zero-norm
    inputs fall back to the plain sum (the projection is undefined)."""
    dot = jnp.sum(a * b)
    na = jnp.sum(a * a)
    nb = jnp.sum(b * b)
    fa = jnp.where(na > 0, 1.0 - dot / (2.0 * jnp.maximum(na, _EPS)), 1.0)
    fb = jnp.where(nb > 0, 1.0 - dot / (2.0 * jnp.maximum(nb, _EPS)), 1.0)
    return fa * a + fb * b


def adasum_combine(stack):
    """Reduce ``[m, D]`` stacked contributions to ``[D]`` by pairwise
    adaptive summation up a binary tree (log2(m) levels; an odd
    leftover at any level rides up unmerged).  Deterministic: the tree
    shape depends only on ``m``."""
    stack = jnp.asarray(stack, jnp.float32)
    while stack.shape[0] > 1:
        m = stack.shape[0]
        pairs = m // 2
        merged = jax.vmap(_adasum_pair)(stack[0:2 * pairs:2],
                                        stack[1:2 * pairs:2])
        if m % 2:
            merged = jnp.concatenate([merged, stack[-1:]], axis=0)
        stack = merged
    return stack[0]


# ------------------------------------------------------------- codecs


def int8_encode(x):
    """Symmetric per-row int8 quantization of ``x [..., C]`` over its
    last axis: returns ``(q int8, scale f32[..., 1])`` with
    ``dequant = q * scale``.  scale = amax/127, guarded so an all-zero
    row encodes to zeros exactly."""
    x = jnp.asarray(x, jnp.float32)
    scale = jnp.max(jnp.abs(x), axis=-1, keepdims=True) / 127.0
    scale = jnp.maximum(scale, _EPS)
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, scale


def int8_decode(q, scale):
    return q.astype(jnp.float32) * scale


# ------------------------------------------------ in-shard_map merges


def _merge_bucket_mean(bucket, axis):
    """Plain mean merge of one local bucket (each replica's full
    ``[n, C]`` contribution) — explicit spelling of the compiler's
    gradient all-reduce, for the stacked-local-grad path."""
    return jax.lax.pmean(bucket, axis)


def _merge_bucket_adasum(bucket, axis):
    """Adasum merge of one local bucket: gather every replica's
    contribution, pairwise-combine up the binary tree (replicated
    math, identical on every replica)."""
    stacked = jax.lax.all_gather(bucket, axis, axis=0)      # [n, n, C]
    merged = adasum_combine(stacked.reshape(stacked.shape[0], -1))
    return merged.reshape(bucket.shape).astype(bucket.dtype)


def _merge_bucket_int8(bucket, e1, e2, axis, n, zero1):
    """Error-feedback int8 merge of one local bucket ``[n, C]`` (rows
    chunk-major: row k is the chunk replica k owns — the Zero1Layout
    contract, which is what makes the two-phase reduce a compressed
    RS+AG).

    Phase 1 (compressed reduce-scatter): quantize each row of the
    residual-corrected local contribution, all-to-all the int8 rows so
    replica k receives every peer's chunk k, dequantize and sum —
    replica k now holds the reduced chunk k.  Phase 2 (compressed
    all-gather; skipped under ``zero1``, which updates on the scattered
    chunks and gathers the f32 *update* instead): re-quantize the
    reduced chunk, all-gather the int8 chunks, dequantize into the full
    merged bucket.  Residuals carry what quantization dropped.

    Returns ``(merged, e1', e2')``: merged is the full ``[n, C]``
    mean bucket (or the ``[C]`` owned chunk under zero1).
    """
    x = jnp.asarray(bucket, jnp.float32) / n + e1   # mean semantics
    q, scale = int8_encode(x)                       # [n, C], [n, 1]
    e1_new = x - int8_decode(q, scale)
    qt = jax.lax.all_to_all(q, axis, split_axis=0, concat_axis=0,
                            tiled=True)             # rows = peers' chunk k
    st = jax.lax.all_to_all(scale, axis, split_axis=0, concat_axis=0,
                            tiled=True)             # [n, 1]
    chunk = jnp.sum(int8_decode(qt, st), axis=0)    # [C]: reduced chunk k
    if zero1:
        return chunk, e1_new, e2
    y = chunk + e2
    q2, s2 = int8_encode(y[None])                   # [1, C], [1, 1]
    e2_new = y - int8_decode(q2, s2)[0]
    qg = jax.lax.all_gather(q2[0], axis, axis=0)    # [n, C] int8
    sg = jax.lax.all_gather(s2[0], axis, axis=0)    # [n, 1]
    merged = int8_decode(qg, sg).astype(bucket.dtype)
    return merged, e1_new, e2_new


def _merge_bucket_topk(bucket, e1, axis, n, k):
    """Error-feedback top-k merge of one local bucket ``[n, C]``: keep
    the ``k`` largest-magnitude entries of the residual-corrected local
    contribution (flattened), all-gather ``(values, indices)`` and
    scatter-add into the dense merged bucket.  Wire per step is
    ``8k * n`` bytes instead of the bucket's f32 all-reduce."""
    shape = bucket.shape
    x = (jnp.asarray(bucket, jnp.float32) / n + e1).reshape(-1)
    _, idx = jax.lax.top_k(jnp.abs(x), k)
    vals = x[idx]
    e_new = x.at[idx].set(0.0).reshape(shape)
    vg = jax.lax.all_gather(vals, axis, axis=0)     # [n, k]
    ig = jax.lax.all_gather(idx, axis, axis=0)      # [n, k]
    merged = jnp.zeros(x.shape, jnp.float32).at[ig.reshape(-1)].add(
        vg.reshape(-1))
    return merged.reshape(shape).astype(bucket.dtype), e_new


# --------------------------------------------------- the optimizer wrap


def _unstacked_struct(stacked):
    """ShapeDtypeStruct tree of the un-stacked gradient (drop the
    leading replica axis) — what the bucket layout is computed over."""
    return jax.tree.map(
        lambda g: jax.ShapeDtypeStruct(tuple(g.shape)[1:], g.dtype),
        stacked)


def resolve_codecs(rules: Sequence, tree, names=None):
    """Per-leaf codec tree from ordered ``(pattern, codec)`` rules via
    the shared rule engine (``parallel/rules.py``): first-match-wins
    over the flattened key paths — or over ``names``, a same-structure
    tree of explicit leaf names (the Keras trainers pass their variable
    paths so rules read ``"dense_1/kernel"``-style, not list indices).
    An unmatched leaf raises, naming it."""
    from distkeras_tpu.parallel.rules import (UnmatchedLeafError,
                                              compile_rules, first_match,
                                              match_rules)

    if names is None:
        return match_rules(list(rules), tree, what="codec")
    compiled = compile_rules(list(rules))

    def of(name):
        matched, codec = first_match(compiled, str(name))
        if not matched:
            raise UnmatchedLeafError(str(name), "codec",
                                     [p.pattern for p, _ in compiled])
        return codec

    return jax.tree.map(of, names)


def exchange_layout(tree, n: int, config: ExchangeConfig, names=None
                    ) -> Zero1Layout:
    """The fusion-bucket layout one exchange policy uses for ``tree``:
    the plain ZeRO-1 layout, except under codec RULES the buckets
    additionally group by resolved codec (``Zero1Layout`` groups=), so
    each bucket is codec-homogeneous and ``bucket_groups[i]`` IS bucket
    i's codec."""
    if config.codec_rules is None:
        return Zero1Layout.for_tree(tree, n, config.bucket_mb)
    codecs = resolve_codecs(config.codec_rules, tree, names=names)
    return Zero1Layout.for_tree(tree, n, config.bucket_mb,
                                groups=codecs)


def _bucket_codecs(layout: Zero1Layout, config: ExchangeConfig) -> list:
    """Bucket index -> codec (or None): the rule-resolved group key
    under codec rules, the uniform ``compress`` otherwise."""
    if config.codec_rules is not None:
        return list(layout.bucket_groups)
    return [config.compress] * len(layout.bucket_cols)


def _e2_slots(layout: Zero1Layout, config: ExchangeConfig,
              zero1: bool) -> dict:
    """bucket index -> slot in the ``e2`` residual list.  Only int8
    buckets outside zero1 carry a phase-2 re-quantization residual —
    a top-k bucket in a mixed-rules layout gets NO slot (an aligned
    zero buffer would persist bucket-sized dead f32 in the optimizer
    state, donated and resharded every step)."""
    if zero1:
        return {}
    codecs = _bucket_codecs(layout, config)
    return {i: k for k, i in enumerate(
        j for j, c in enumerate(codecs) if c == "int8")}


def _residual_shapes(layout: Zero1Layout, config: ExchangeConfig,
                     zero1: bool):
    """(e1 shapes, e2 shapes) — global — for one layout.  ``e1``
    exists per bucket for every codec'd bucket; ``e2`` per int8 bucket
    only (see :func:`_e2_slots`)."""
    n = layout.n
    codecs = _bucket_codecs(layout, config)
    if not any(codecs):
        return [], []
    e1 = [(n, n, c) for c in layout.bucket_cols]
    e2 = [(n, layout.bucket_cols[i])
          for i in sorted(_e2_slots(layout, config, zero1))]
    return e1, e2


def topk_k(config: ExchangeConfig, bucket_cols: int, n: int) -> int:
    """Entries kept per bucket: ``topk_frac`` of the bucket, >= 1."""
    return max(1, int(round(config.topk_frac * bucket_cols * n)))


def wire_bytes(layout: Zero1Layout, config: ExchangeConfig,
               zero1: bool = False) -> tuple[int, int]:
    """``(baseline_bytes, wire_bytes)`` of one GRADIENT exchange under
    ``config`` for this bucket layout, ring-model per-device — the same
    accounting as the compiled collective census (all-reduce moves
    ``2(n-1)/n x payload``, one-shot collectives ``(n-1)/n``;
    scripts/comm_budget.json pins the compiled truth, this is what the
    obs gauges and the ``lowcomm_update`` bench report).

    ``baseline_bytes`` is the mean exchange's wire (the f32 gradient
    all-reduce; under ``zero1`` its reduce-scatter leg — the leg the
    int8 codec compresses).  ``wire_bytes`` counts the configured
    rule's gradient legs: int8 = int8 payload + per-row f32 scales per
    leg; top-k = the ``(values, indices)`` all-gather; adasum = the
    whole-stack all-gather (MORE than the mean — the batch-scaling
    trade, visible by design)."""
    n = layout.n
    ring = (n - 1) / n
    payloads = [c * n * np.dtype(d).itemsize
                for c, d in zip(layout.bucket_cols,
                                layout.bucket_dtypes)]
    ar_legs = 1 if zero1 else 2
    f32_bytes = int(sum(ar_legs * ring * p for p in payloads))
    codecs = _bucket_codecs(layout, config)
    wire = 0.0
    for cols, payload, codec in zip(layout.bucket_cols, payloads,
                                    codecs):
        if codec == "int8":
            legs = 1 if zero1 else 2
            wire += legs * ring * (cols * n + 4 * n)
        elif codec == "topk":
            wire += ring * 8 * topk_k(config, cols, n) * n
        elif config.merge_rule == "adasum":
            wire += ring * n * payload
        else:
            wire += ar_legs * ring * payload
    return f32_bytes, int(wire)


def _record_geometry(layout: Zero1Layout, config: ExchangeConfig,
                     zero1: bool) -> None:
    """Exchange geometry into the obs registry at TRACE time (once per
    compile) — bucket count, f32 vs wire bytes, compression ratio.
    The census (scripts/comm_budget.json) pins the compiled truth;
    these gauges make it readable on a live run."""
    if obs.active() is None:
        return
    f32_bytes, wire = wire_bytes(layout, config, zero1)
    obs.gauge("exchange.buckets", len(layout.bucket_cols))
    obs.gauge("exchange.f32_bytes", f32_bytes)
    obs.gauge("exchange.wire_bytes", wire)
    obs.gauge("exchange.compression_ratio",
              f32_bytes / max(wire, 1))
    obs.gauge("exchange.sync_every", config.sync_every)
    codecs = _bucket_codecs(layout, config)
    obs.event("exchange.geometry", merge_rule=config.merge_rule,
              codec=("rules" if config.codec_rules is not None
                     else config.compress or "none"), zero1=zero1,
              buckets=len(layout.bucket_cols),
              bucket_codecs=",".join(str(c) for c in codecs))


def exchange_optimizer(inner: optax.GradientTransformation, mesh: Mesh,
                       config: ExchangeConfig, axis: str = "data",
                       zero1: bool = False, names=None
                       ) -> optax.GradientTransformation:
    """Wrap ``inner`` so its ``update`` takes STACKED LOCAL gradients
    (leading replica axis, sharded ``P(axis)``) and performs the
    configured exchange before the inner update.

    ``state = (inner_state, ExchangeState)``.  Without ``zero1`` the
    merged gradient is replicated and ``inner`` runs replicated (its
    state mirrors the params exactly as in plain DP).  With ``zero1``
    the compressed phase-1 reduce leaves each replica its owned chunk,
    ``inner`` runs on the scattered ``[n, cols]`` shard views (the
    ZeRO-1 layout), and the f32 *update* is all-gathered — the
    "compress the reduce-scatter leg" composition.

    Under codec RULES (``config.compress`` a ``(pattern, codec)``
    sequence) each fusion bucket runs the codec its leaves resolved to;
    ``names`` optionally names the leaves for the rules (a tree of
    strings matching the parameter structure — the Keras trainers pass
    their variable paths; by default the flattened key paths name
    them).

    The returned transform's ``init`` takes the plain (un-stacked)
    parameter tree, like any optax transform.
    """
    n = int(mesh.shape[axis])
    if zero1 and config.compress != "int8":
        raise ValueError(
            "zero1 composes with compress='int8' only (the chunked "
            "two-phase codec IS a compressed reduce-scatter; adasum, "
            "top-k and per-bucket codec rules merge whole buckets)")

    def layout_for(tree) -> Zero1Layout:
        return exchange_layout(tree, n, config, names=names)

    def init(params):
        layout = layout_for(params)
        inner_state = inner.init(layout.shard_views(params) if zero1
                                 else params)
        e1_s, e2_s = _residual_shapes(layout, config, zero1)
        ex = ExchangeState(
            e1=tuple(jnp.zeros(s, jnp.float32) for s in e1_s),
            e2=tuple(jnp.zeros(s, jnp.float32) for s in e2_s),
            residual_norm=jnp.zeros((), jnp.float32))
        return inner_state, ex

    def _merge(stacked, ex: ExchangeState, layout: Zero1Layout):
        """shard_map over ``axis``: local grads -> merged grads (full
        tree, or scattered buckets under zero1) + new residuals."""
        codecs = _bucket_codecs(layout, config)
        e2_slot = _e2_slots(layout, config, zero1)

        def body(stacked_local, e1, e2):
            g = jax.tree.map(lambda v: jnp.squeeze(v, axis=0),
                             stacked_local)
            buckets = layout.pack(g)
            e1 = [jnp.squeeze(e, axis=0) for e in e1]
            e2 = [jnp.squeeze(e, axis=0) for e in e2]
            merged, e1_new, e2_new = [], [], []
            for i, b in enumerate(buckets):
                if codecs[i] == "int8":
                    m, r1, r2 = _merge_bucket_int8(
                        b, e1[i],
                        e2[e2_slot[i]] if i in e2_slot else 0.0,
                        axis, n, zero1)
                    e1_new.append(r1)
                    if i in e2_slot:  # appended in slot order
                        e2_new.append(r2)
                elif codecs[i] == "topk":
                    k = topk_k(config, layout.bucket_cols[i], n)
                    m, r1 = _merge_bucket_topk(b, e1[i], axis, n, k)
                    e1_new.append(r1)
                elif config.merge_rule == "adasum":
                    m = _merge_bucket_adasum(b, axis)
                else:
                    m = _merge_bucket_mean(b, axis)
                merged.append(m)
            if e1_new or e2_new:
                sq = sum(jnp.sum(jnp.square(e)) for e in e1_new + e2_new)
                norm = jnp.sqrt(jax.lax.psum(sq, axis))
            else:  # no codec: no residual, and no wasted scalar AR
                norm = jnp.zeros(())
            if zero1:
                # merged[i] is this replica's [C] chunk; keep a leading
                # row axis so the out_spec shards it back into the
                # scattered [n, C] bucket layout.
                out = [m[None] for m in merged]
            else:
                out = layout.unpack(merged)
            return (out,
                    [e[None] for e in e1_new],
                    [e[None] for e in e2_new],
                    norm)

        merged_spec = P(axis, None) if zero1 else P()
        return shard_map(
            body, mesh=mesh,
            in_specs=(P(axis), P(axis), P(axis)),
            out_specs=(merged_spec, P(axis), P(axis), P()),
            check_vma=False)(stacked, list(ex.e1), list(ex.e2))

    def update(stacked_grads, state, params=None, **kw):
        inner_state, ex = state
        layout = layout_for(_unstacked_struct(stacked_grads))
        _record_geometry(layout, config, zero1)
        with jax.named_scope("exchange/merge"):
            merged, e1, e2, norm = _merge(stacked_grads, ex, layout)
        ex = ExchangeState(e1=tuple(e1), e2=tuple(e2),
                           residual_norm=norm)
        if zero1:
            g_views = layout.views_from_buckets(merged)
            p_views = (None if params is None
                       else layout.shard_views(params))
            with jax.named_scope("exchange/update"):
                u_views, inner_state = inner.update(g_views, inner_state,
                                                    p_views, **kw)
            with jax.named_scope("exchange/all_gather"):
                u_buckets = [all_gather(b, mesh, axis)
                             for b in layout.pack_views(u_views)]
            updates = layout.unpack(u_buckets)
        else:
            with jax.named_scope("exchange/update"):
                updates, inner_state = inner.update(merged, inner_state,
                                                    params, **kw)
        return updates, (inner_state, ex)

    return optax.GradientTransformation(init, update)


# ----------------------------------------------------- state shardings


def exchange_state_shardings(params, opt_state, mesh: Mesh,
                             axis: str = "data", zero1: bool = False):
    """Sharding tree for an :func:`exchange_optimizer` state: residual
    leaves shard over their leading replica axis, zero1 shard views
    (when composed) take the ZeRO shard-view rule, everything else
    replicates.  Since the ZeRO-2/3 round the policy is ordered rules
    resolved by the shared engine (``parallel/rules.py``) — the path-
    keyed ``e1``/``e2`` residual rules inside the located
    :class:`ExchangeState`, the shape-keyed shard-view rule outside.
    ``opt_state`` may be real arrays or an ``eval_shape`` tree."""
    from distkeras_tpu.parallel.rules import (match_rules,
                                              shard_view_rule)

    rep = NamedSharding(mesh, P())
    ex_rules = [
        (r"(^|/)e1(/|$)", NamedSharding(mesh, P(axis, None, None))),
        (r"(^|/)e2(/|$)", NamedSharding(mesh, P(axis, None))),
        (r".*", rep),
    ]
    inner_rules = []
    if zero1:
        shapes = zero1_shard_shapes(list(jax.tree.leaves(params)),
                                    int(mesh.shape[axis]))
        inner_rules.append(shard_view_rule(shapes, mesh, axis=axis))
    inner_rules.append((r".*", rep))

    def rule(x):
        if isinstance(x, ExchangeState):
            # The residual rules match within the ExchangeState subtree
            # only — a user parameter named "e1" elsewhere can never
            # collide with them.
            return match_rules(ex_rules, x, what="exchange sharding")
        return match_rules(inner_rules, {"leaf": x},
                           what="exchange sharding")["leaf"]

    return jax.tree.map(rule, opt_state,
                        is_leaf=lambda x: isinstance(x, ExchangeState))


def residual_norm_of(opt_state):
    """The ExchangeState residual-norm scalar buried anywhere in an
    optimizer state, or None.  Host-side, end-of-run: the trainers
    record it into the obs registry as the EF diagnostic."""
    found = []

    def visit(x):
        if isinstance(x, ExchangeState):
            found.append(x.residual_norm)
        return x

    jax.tree.map(visit, opt_state,
                 is_leaf=lambda x: isinstance(x, ExchangeState))
    return float(found[0]) if found else None


# --------------------------------------------------- local-SGD merging


def _mean_buckets(tree, axis: str, n: int, bucket_mb: float):
    """pmean a pytree through the fusion-bucket layout: pack, ONE
    pmean per bucket, unpack.  This is what keeps a local-SGD sync at
    ~one collective per bucket instead of one per leaf — the whole
    point of trading per-step gradient exchange for a periodic merge."""
    layout = Zero1Layout.for_tree(
        jax.tree.map(lambda d: jax.ShapeDtypeStruct(d.shape, d.dtype),
                     tree), n, bucket_mb)
    buckets = [jax.lax.pmean(b, axis) for b in layout.pack(tree)]
    return layout.unpack(buckets)


def merge_local_params(start, local, config: ExchangeConfig, axis: str,
                       n: int):
    """Cross-replica parameter merge at a local-SGD sync point, INSIDE
    a shard_map over ``axis``: ``start`` is the (replicated) tree the
    period began from, ``local`` the replica's diverged tree.  The
    merge applies the configured rule to the parameter DELTAS, per
    fusion bucket — ``mean`` averages them (classic local-SGD /
    federated averaging); ``adasum`` combines them adaptively, the
    Adasum paper's own suggested use beyond gradients."""
    delta = jax.tree.map(lambda a, b: b - a, start, local)
    if config.merge_rule == "mean":
        merged = _mean_buckets(delta, axis, n, config.bucket_mb)
    else:
        layout = Zero1Layout.for_tree(
            jax.tree.map(
                lambda d: jax.ShapeDtypeStruct(d.shape, d.dtype), delta),
            n, config.bucket_mb)
        buckets = [_merge_bucket_adasum(b, axis)
                   for b in layout.pack(delta)]
        merged = layout.unpack(buckets)
    return jax.tree.map(jnp.add, start, merged)


def sync_local_tree(tree, config: ExchangeConfig, axis: str, n: int):
    """Momentum-aware half of the sync: pmean every floating leaf of
    ``tree`` (an optimizer state / ntv pytree) through the fusion
    buckets, pass the rest through (int leaves — step counts —
    increment identically on every replica)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    fmask = [jnp.issubdtype(jnp.asarray(l).dtype, jnp.inexact)
             for l in leaves]
    floats = [l for l, m in zip(leaves, fmask) if m]
    if floats:
        merged = iter(_mean_buckets(floats, axis, n, config.bucket_mb))
        leaves = [next(merged) if m else l
                  for l, m in zip(leaves, fmask)]
    return jax.tree_util.tree_unflatten(treedef, leaves)


__all__ = ["ExchangeConfig", "ExchangeState", "exchange_optimizer",
           "exchange_state_shardings", "exchange_layout",
           "resolve_codecs", "residual_norm_of",
           "adasum_combine", "int8_encode", "int8_decode",
           "merge_local_params", "sync_local_tree",
           "topk_k", "wire_bytes"]
