"""Device mesh construction (L2' — replaces the reference's transport).

The reference's communication fabric is a hand-rolled star of TCP
sockets between Spark executors and a driver-side parameter server
(reference: distkeras/networking.py — connect/send_data/recv_data — and
distkeras/parameter_servers.py).  The TPU-native equivalent is a
``jax.sharding.Mesh`` over the device grid: collectives (psum /
all-gather / reduce-scatter) are emitted by XLA from sharding
annotations and ride the ICI torus, with DCN used automatically across
pod slices.  There is deliberately *no* user-level transport code in
this package — deleting the pickle-over-TCP hot path is the point
(SURVEY.md §3.2 identifies it as the reference's scalability
bottleneck).

Multi-host: call :func:`initialize_multihost` once per host process
before building a mesh; ``jax.devices()`` then spans the whole pod and
the same MeshSpec code path produces a global mesh.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import numpy as np
from jax.sharding import Mesh


# Canonical axis names, in mesh order.  data = batch (DP replicas),
# model = tensor parallelism, pipeline/seq/expert reserved for the wider
# parallelism surface (PP/SP/EP) layered on the same mesh.
AXES = ("data", "model", "pipeline", "seq", "expert")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape.  ``-1`` on ``data`` means "all remaining devices".

    Only axes with size > 1 consume devices; every axis is always present
    in the mesh so PartitionSpecs can name them unconditionally.
    """

    data: int = -1
    model: int = 1
    pipeline: int = 1
    seq: int = 1
    expert: int = 1

    def resolve(self, n_devices: int) -> tuple[int, ...]:
        fixed = self.model * self.pipeline * self.seq * self.expert
        if n_devices % fixed:
            raise ValueError(
                f"{n_devices} devices not divisible by model*pipeline*seq*expert={fixed}")
        data = self.data if self.data != -1 else n_devices // fixed
        total = data * fixed
        if total != n_devices:
            raise ValueError(
                f"MeshSpec {self} needs {total} devices, have {n_devices}")
        return (data, self.model, self.pipeline, self.seq, self.expert)


def make_mesh(spec: MeshSpec | None = None, devices=None) -> Mesh:
    """Build a ``jax.sharding.Mesh`` from a :class:`MeshSpec`.

    Device order follows ``jax.devices()`` which JAX already orders for
    ICI locality on TPU; the innermost mesh axes get the nearest
    neighbours, so put the highest-bandwidth-hungry axis (model) after
    data when both are >1.
    """
    spec = spec or MeshSpec()
    devices = np.asarray(devices if devices is not None else jax.devices())
    shape = spec.resolve(devices.size)
    return Mesh(devices.reshape(shape), AXES)


def local_device_count() -> int:
    return jax.local_device_count()


def initialize_multihost(coordinator_address: str | None = None,
                         num_processes: int | None = None,
                         process_id: int | None = None) -> None:
    """Join a multi-host JAX runtime (one call per host process).

    Replaces the reference's process-management inheritance from Spark
    (SURVEY.md §5: Spark executors host the workers).  On TPU pods the
    hosts coordinate through ``jax.distributed``; afterwards
    ``jax.devices()`` is global and every mesh built here spans the pod.

    No-op when running single-process (the common dev/test case).
    """
    if num_processes is None or num_processes <= 1:
        return
    enable_cpu_collectives()
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def enable_cpu_collectives() -> None:
    """Select the gloo backend for cross-process CPU collectives.

    The pinned jax (0.4.37) ships multiprocess CPU support but does not
    enable it by default — without this, any cross-process psum on the
    CPU backend dies with "Multiprocess computations aren't implemented
    on the CPU backend".  Must run BEFORE ``jax.distributed.initialize``.
    Guarded: on accelerator backends the option is irrelevant, and a
    future jax that renames or removes it must not break multihost
    init on real hardware."""
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # noqa: BLE001 — option gone/renamed: proceed
        pass


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return int(mesh.shape[axis])


def global_batch(arr, sharding):
    """Host batch -> device batch across the (possibly multi-host) mesh.

    Single-process: plain ``device_put`` under the sharding.
    Multi-process SPMD (the Spark-executor analogue, SURVEY.md §5):
    every process holds only ITS rows (its ``Dataset.shard``), so the
    global array is assembled from the process-local slab — each host's
    rows land on its own devices and the collectives do the rest.  The
    single shared definition: the trainer family and LMTrainer both
    route batches through here.
    """
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    return jax.make_array_from_process_local_data(sharding, arr)


def equal_across_hosts(local_count: int, what: str) -> int:
    """Assert every process computed the same ``local_count``; returns it.

    The ONE definition of the lockstep-safety check the multi-process
    paths share (streaming rounds, eval shard sizes, device-resident
    usable windows): a host that would run more collective iterations
    than its peers deadlocks the mesh, so the imbalance must raise on
    EVERY host — the allgather here is itself collective, but it runs
    before the loop, while all processes still agree.  No-op (no
    collective) single-process.
    """
    if jax.process_count() == 1:
        return local_count
    import numpy as np
    from jax.experimental import multihost_utils

    counts = [int(c) for c in multihost_utils.process_allgather(
        np.asarray(local_count, np.int64))]
    if len(set(counts)) != 1:
        raise ValueError(
            f"unequal {what} across processes: {counts} — every host "
            "must contribute the same count or the collectives "
            "deadlock; pad or trim the per-host shards")
    return local_count


def per_host_rows(global_bs: int, what: str = "global batch") -> int:
    """Rows each process feeds per global batch: ``global_bs /
    process_count``, validated to divide evenly (shared by the
    streaming, eval-chunk, and device-resident staging geometry)."""
    pcount = jax.process_count()
    if global_bs % pcount:
        raise ValueError(
            f"{what} {global_bs} (batch_size x num_workers) must "
            f"divide by the process count ({pcount})")
    return global_bs // pcount
