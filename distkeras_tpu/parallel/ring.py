"""Ring attention: sequence/context parallelism over the mesh ``seq`` axis.

The reference caps sequence length at single-device memory (its longest
sequence model is the IMDB LSTM at maxlen=128; reference: examples).
Here long context is first-class: the sequence dimension is sharded over
the mesh ``seq`` axis and attention runs as a ring — each device holds
its Q shard permanently plus a rotating KV shard, updates flash-style
online-softmax state (distkeras_tpu.ops.attention.attention_chunk), and
``ppermute``s the KV block to its ring neighbour.  After ``seq`` hops
every Q row has attended to the full global sequence while per-device
memory stays O(L/seq).  The KV transfer rides the ICI ring concurrently
with the chunk matmuls (XLA overlaps the ppermute DMA with compute).

This is the Ring Attention construction (Liu et al., 2023 — see
PAPERS.md); the blockwise core it rotates is shared with the Pallas
flash kernel so single-device and ring numerics match by construction.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from distkeras_tpu.parallel.compat import shard_map

from distkeras_tpu.ops.attention import (
    attention_chunk,
    online_finish,
    online_init,
    _check_window,
    _scale_for,
)


def ring_attention(q, k, v, axis_name: str = "seq", causal: bool = False,
                   scale: float | None = None, window: int | None = None,
                   segment_ids=None):
    """Per-shard ring attention body; call inside ``shard_map``.

    ``q/k/v: [B, L_local, H, D]`` — the local shard of a sequence of
    global length ``L_local * axis_size``.  Returns the local shard of
    the attention output.

    ``window`` (causal sliding window) masks on *global* positions via
    the per-hop offsets, so ring + local attention composes exactly
    with the single-device result; hops whose KV shard lies entirely
    beyond the lookback contribute nothing (masked, still rotated —
    the ring must complete for the other devices).

    ``segment_ids [B, L_local]`` (the local shard of packed-document
    ids): the query-side shard stays put and a KV-side copy rotates
    around the ring WITH its K/V shard, so every hop masks exactly the
    cross-document pairs the single-device computation would — packed
    long-context training over the seq axis.
    """
    _check_window(window, causal)
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, lq, h, d = q.shape
    lk = k.shape[1]
    s = _scale_for(q, scale)
    qf = q.astype(jnp.float32)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    # Segmented-ness is static at trace time: the unsegmented carry
    # simply has no segment slot (no dead ppermute per hop).
    segmented = segment_ids is not None

    def update(m, l, o, kc, vc, sc, hop):
        # After `hop` rotations we hold the KV shard originally on
        # (my_idx - hop) mod axis_size; offsets make causal masking
        # global-position-correct.
        src = (my_idx - hop) % axis_size
        return attention_chunk(
            qf, kc.astype(jnp.float32), vc.astype(jnp.float32), m, l, o,
            causal, s, q_offset=my_idx * lq, kv_offset=src * lk,
            window=window, seg_q=segment_ids, seg_k=sc)

    def body(carry, hop):
        m, l, o, kc, vc, *sc = carry
        m, l, o = update(m, l, o, kc, vc, sc[0] if segmented else None,
                         hop)
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        if segmented:
            sc = [jax.lax.ppermute(sc[0], axis_name, perm)]
        return (m, l, o, kc, vc, *sc), None

    # The last hop consumes its KV shard without rotating it onward —
    # scanning all `axis_size` hops would send one extra KV shard per
    # device over the ICI for nothing.
    init = (*online_init(b, h, lq, d), k, v) + (
        (segment_ids,) if segmented else ())
    (m, l, o, kc, vc, *sc), _ = jax.lax.scan(
        body, init, jnp.arange(axis_size - 1))
    m, l, o = update(m, l, o, kc, vc, sc[0] if segmented else None,
                     axis_size - 1)
    return online_finish(m, l, o).astype(q.dtype)


def make_ring_attention(mesh: Mesh, axis_name: str = "seq",
                        batch_axis: str | None = "data",
                        causal: bool = False, scale: float | None = None,
                        window: int | None = None):
    """Wrap :func:`ring_attention` in shard_map over ``mesh``.

    Returns ``f(q, k, v) -> out`` taking/returning global arrays of
    shape [B, L, H, D]; batch is sharded over ``batch_axis``, sequence
    over ``axis_name``, heads/dim replicated.  Composes under an outer
    jit/pjit — tensor parallelism on the H axis can be layered by
    sharding the projection weights, not this function.

    Do NOT call this wrapper inside another shard_map (a nested
    shard_map does not transpose under AD): code that is already manual
    over ``axis_name`` — the PP x SP pipeline — calls the raw
    :func:`ring_attention` body directly instead
    (transformer.apply_pipelined's ``seq_axis``).
    """
    fn = functools.partial(ring_attention, axis_name=axis_name,
                           causal=causal, scale=scale, window=window)
    spec = P(batch_axis, axis_name, None, None)
    mapped = shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    seg_spec = P(batch_axis, axis_name)
    mapped_seg = shard_map(
        lambda q, k, v, seg: fn(q, k, v, segment_ids=seg),
        mesh=mesh, in_specs=(spec, spec, spec, seg_spec),
        out_specs=spec, check_vma=False)

    def ring_fn(q, k, v, segment_ids=None):
        if segment_ids is None:
            return mapped(q, k, v)
        return mapped_seg(q, k, v, segment_ids)

    # Tells apply_hidden's window guard WHICH window this attention_fn
    # implements; the guard requires it to equal cfg.attention_window
    # (a mismatched band would silently diverge train from decode).
    ring_fn.handles_window = window
    # Tells _resolve_attention_fn this fn accepts packed segment_ids
    # (it wraps the per-call segments in; fns without the attribute
    # are rejected rather than silently skipping the attention mask).
    ring_fn.handles_segments = True
    return ring_fn


def sequence_sharding(mesh: Mesh, batch_axis: str | None = "data",
                      axis_name: str = "seq") -> NamedSharding:
    """NamedSharding for [B, L, ...] activations under ring attention."""
    return NamedSharding(mesh, P(batch_axis, axis_name))
