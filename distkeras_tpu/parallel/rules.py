"""Regex partition-rule plans: ordered rules over flattened key paths.

The repo grew three hand-built planners — ``ShardingPlan.spec_for``'s
regex loop over Keras variable paths, ``Zero1Plan``'s shape-keyed
optimizer-state walk, and ``ExchangePlan``'s residual-aware variant —
and the ZeRO-2/3 work multiplies the plans again.  This module is the
ONE rule engine they all derive from, the ``match_partition_rules``
pattern of SNIPPETS [1] grown into a library:

* a **rule** is ``(pattern, value)``: ``pattern`` a regex matched
  (``re.search``) against the leaf's flattened key path (rendered
  ``"layers/0/attn/wq"``-style, the same language ShardingPlan always
  used), ``value`` either a concrete value (a ``PartitionSpec``, a
  codec name, ...) or a callable ``(name, leaf) -> value | None`` —
  ``None`` means "this rule declines, fall through to the next".
  Callable rules are what lets shape-keyed policies (the ZeRO shard-view
  rule) and path-keyed policies live in one ordered list.
* matching is **first-match-wins** in rule order;
* an **unmatched leaf raises**, naming the leaf path — the silent
  "unmatched means replicated" default of the old planners hid typos in
  TP rule sets.  Pass ``default=`` to restore a fallback explicitly
  (the plans append an explicit catch-all ``(".*", default)`` instead,
  so reading the rule list shows the whole policy).

Consumers: ``parallel/sharding.py`` (every ShardingPlan;
``Zero3Plan``), ``parallel/exchange.py`` (per-bucket codec rules and
the exchange-state shardings), and user code via
``distkeras_tpu.match_partition_rules``.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distkeras_tpu.parallel.compat import keystr


class UnmatchedLeafError(ValueError):
    """No rule matched a leaf (carries the rendered leaf path).

    ``patterns`` (when the raiser has them) are the rule patterns that
    were tried: the message names the 3 nearest misses by match-prefix
    length — how far the pattern's literal spelling gets into the leaf
    path before diverging — so a plan-authoring typo ("atn/wq" for
    "attn/wq") is self-diagnosing instead of a silent fall-through.
    """

    def __init__(self, name: str, what: str, patterns: Sequence[str] = ()):
        self.leaf = name
        near = nearest_patterns(name, patterns)
        near_s = ("; nearest-miss patterns (by match-prefix length): "
                  + ", ".join(repr(p) for p in near)) if near else ""
        super().__init__(
            f"no {what} rule matched leaf {name!r}; rules are ordered "
            "(pattern, value) pairs matched first-match-wins against "
            "the flattened key path — add a rule for this leaf or a "
            f"catch-all ('.*', <default>) at the end{near_s}")


def _pattern_skeleton(pattern: str) -> str:
    """The literal spine of a regex: metacharacters stripped, escapes
    unwrapped — what the author *typed* minus the regex machinery."""
    s = re.sub(r"\\([\w/])", r"\1", pattern)
    return re.sub(r"[\^\$\.\*\+\?\(\)\[\]\{\}\|\\]", "", s)


def _miss_score(pattern: str, name: str) -> int:
    """Match-prefix length: the longest prefix of the pattern's literal
    skeleton that still occurs in ``name``.  A typo'd rule scores just
    below its intended target; an unrelated rule scores ~0."""
    skel = _pattern_skeleton(pattern)
    for k in range(len(skel), 0, -1):
        if skel[:k] in name:
            return k
    return 0


def nearest_patterns(name: str, patterns: Sequence[str], n: int = 3):
    """The ``n`` patterns nearest to ``name`` by match-prefix length
    (ties keep rule order) — the UnmatchedLeafError diagnosis.
    Patterns sharing nothing with the leaf (score 0) are omitted:
    listing unrelated rules as "nearest" would mislead, and an empty
    result drops the diagnosis line entirely."""
    pats = [p if isinstance(p, str) else p.pattern for p in patterns]
    scored = sorted((-_miss_score(p, name), i)
                    for i, p in enumerate(pats))
    return [pats[i] for s, i in scored[:n] if s < 0]


# Sentinel: "no default — unmatched leaves are an error".
_RAISE = object()


def leaf_name(path) -> str:
    """Render one jax key path the way every rule in this repo is
    written against: ``"layers/0/attn/wq"``."""
    return keystr(path, simple=True, separator="/")


def _is_concrete(val) -> bool:
    """A value that always claims a pattern match (a PartitionSpec, a
    codec name, a sharding) — as opposed to a callable rule, which may
    decline and fall through.  The shard lint's duplicate-pattern rule
    (analysis/shard_lint.py) shares this predicate so the build-time
    rejection below and the static lint can never disagree."""
    return not (callable(val) and not isinstance(val, type))


def compile_rules(rules: Sequence[tuple[str, Any]]):
    """[(pattern, value)] -> [(compiled, value)], validating patterns
    eagerly so a typo raises at plan construction, not mid-trace.

    Rejects an identical pattern repeated after an earlier occurrence
    with a *concrete* value: first-match-wins makes the later rule
    unreachable, so the duplicate is a plan-authoring bug (the same
    spelling as the shard lint's ``duplicate-pattern`` rule,
    docs/graph_lint.md).  Repeats after a *callable* occurrence remain
    legal — the decline-chain idiom ``zero_state_rules`` is built on.
    """
    claimed: dict[str, bool] = {}
    out = []
    for pat, val in rules:
        if claimed.get(pat):
            raise ValueError(
                f"duplicate pattern {pat!r}: an identical earlier rule "
                "with a concrete value already claims every match "
                "(first-match-wins), so this rule can never fire — "
                "remove one of the two (shard lint rule "
                "`duplicate-pattern`)")
        claimed[pat] = claimed.get(pat, False) or _is_concrete(val)
        out.append((re.compile(pat), val))
    return out


def first_match(compiled, name: str, leaf=None):
    """First rule whose pattern matches ``name`` and whose value
    accepts the leaf; ``(matched, value)`` — ``(False, None)`` when no
    rule claims it."""
    for pat, val in compiled:
        if pat.search(name) is None:
            continue
        if callable(val) and not isinstance(val, type):
            out = val(name, leaf)
            if out is None:
                continue  # rule declined: fall through
            return True, out
        return True, val
    return False, None


def match_rules(rules: Sequence[tuple[str, Any]], tree, *,
                default: Any = _RAISE, what: str = "partition"):
    """Pytree -> same-structure pytree of rule values.

    The generic engine: ``rules`` may map to anything (PartitionSpecs,
    codec names, shardings).  Unmatched leaves raise
    :class:`UnmatchedLeafError` naming the leaf, unless ``default`` is
    given.
    """
    compiled = compile_rules(rules)

    def visit(path, leaf):
        name = leaf_name(path)
        matched, val = first_match(compiled, name, leaf)
        if matched:
            return val
        if default is _RAISE:
            raise UnmatchedLeafError(name, what,
                                     [p.pattern for p, _ in compiled])
        return default

    return jax.tree_util.tree_map_with_path(visit, tree)


def match_partition_rules(rules: Sequence[tuple[str, P]], tree, *,
                          default: Any = _RAISE):
    """The SNIPPETS [1] ``match_partition_rules`` contract: ordered
    ``(regex, PartitionSpec)`` rules over flattened key paths, first
    match wins, **scalar leaves always replicate** (partitioning a
    scalar is never meaningful), unmatched non-scalar leaves raise
    naming the leaf."""
    def scalar_guard(name, leaf):
        shape = getattr(leaf, "shape", None)
        if shape is not None and len(shape) == 0:
            return P()
        return None

    return match_rules([(r".*", scalar_guard)] + list(rules), tree,
                       default=default)


def tree_shardings(mesh: Mesh, rules: Sequence[tuple[str, Any]], tree, *,
                   default: Any = _RAISE, what: str = "sharding"):
    """Like :func:`match_rules` but wraps plain ``PartitionSpec``
    values into ``NamedSharding(mesh, spec)`` (values that already are
    shardings pass through) — the form ``jax.device_put`` and
    ``jit(out_shardings=...)`` consume."""
    def wrap(v):
        return NamedSharding(mesh, v) if isinstance(v, P) else v

    if default is not _RAISE:
        default = wrap(default)
    specs = match_rules(rules, tree, default=default, what=what)
    return jax.tree_util.tree_map(wrap, specs)


# --------------------------------------------------- the ZeRO rule set


def shard_view_rule(shard_shapes: frozenset, mesh: Mesh,
                    axis: str = "data"):
    """The ZeRO shard-view rule as ONE engine rule: any leaf whose
    shape is a ``[n, cols]`` shard-view shape of the parameter tree
    scatters ``P(axis, None)``; every other leaf falls through to the
    next rule.  Shape-keyed on purpose (see
    ``collectives.zero1_state_shardings``): it covers moments nested in
    chains, masks and EMA shadows uniformly, because under a sharded
    update the inner optimizer only ever sees shard views."""
    sh = NamedSharding(mesh, P(axis, None))

    def rule(name, leaf):
        if hasattr(leaf, "shape") and tuple(leaf.shape) in shard_shapes:
            return sh
        return None

    return (r".*", rule)


def zero_state_rules(params, mesh: Mesh, axis: str = "data"):
    """The ordered rule list for a ZeRO-sharded optimizer state (every
    stage): shard views scatter, everything else (scalar counts,
    EmptyState internals) replicates.  ``params`` is the parameter tree
    the state mirrors (arrays or shape structs) — full layout or shard
    views, the derived shard shapes agree."""
    from distkeras_tpu.parallel.collectives import zero1_shard_shapes

    shapes = zero1_shard_shapes(jax.tree.leaves(params),
                                int(mesh.shape[axis]))
    return [shard_view_rule(shapes, mesh, axis=axis),
            (r".*", NamedSharding(mesh, P()))]


def zero_state_shardings(params, opt_state, mesh: Mesh,
                         axis: str = "data"):
    """Sharding tree for a ZeRO optimizer state, via the rule engine —
    the ONE definition every stage and both trainer families share."""
    return match_rules(zero_state_rules(params, mesh, axis=axis),
                       opt_state, what="ZeRO state sharding")


def zero3_param_shardings(view_tree, mesh: Mesh, axis: str = "data"):
    """Shardings for a ZeRO-3 parameter tree held as ``[n, cols]``
    shard views: every leaf scatters ``P(axis, None)`` (gather-on-use
    re-materializes them per fusion bucket inside the step)."""
    sh = NamedSharding(mesh, P(axis, None))
    return jax.tree.map(lambda _: sh, view_tree)


# ----------------------------------------------- the serving KV rules
#
# Pod-sharded serving (round 14): the KV cache's placement is DERIVED
# from the param rules, never authored separately — the rule that
# shards attention projections over a mesh axis determines which axis
# the cache's kv-heads dimension shards over, so plan and cache can
# never disagree (a cache sharded differently from the heads that
# write it would make GSPMD reshard the slab every token).

# Canonical attention-projection paths of the functional transformer
# (models/transformer.py init_params), with the index of the HEADS
# dimension in each kernel's [L, ...] stacked shape.  wq carries
# n_heads; wk/wv carry kv_heads — both must divide by the axis.
_ATTN_HEAD_PATHS = (
    ("layers/attn/wq", 2, "n_heads"),
    ("layers/attn/wk", 2, "kv_heads"),
    ("layers/attn/wv", 2, "kv_heads"),
)


def _axes_of(entry) -> tuple:
    """Mesh axes one PartitionSpec entry names (an entry may be an
    axis name or a tuple of them)."""
    if entry is None:
        return ()
    return tuple(entry) if isinstance(entry, tuple) else (entry,)


def serving_kv_axis(plan, mesh: Mesh, cfg) -> str | None:
    """The mesh axis a serving plan shards attention HEADS over — and
    therefore the axis the KV cache/slab's kv-heads dimension must
    shard over.  None when the plan leaves attention heads whole
    (pure-FSDP / replicated plans: params gather on use, the cache
    replicates, GSPMD still compiles one program).

    Validates head divisibility eagerly and names the offending rule:
    a head count the axis cannot split would otherwise surface as an
    inscrutable GSPMD error at first trace.
    """
    axis, culprit = None, None
    for path, head_dim, attr in _ATTN_HEAD_PATHS:
        for pat, spec in plan.rules:
            if pat.search(path) is None:
                continue
            if callable(spec):
                # First-match-wins: a callable claiming an attention
                # path would decide the param placement at device_put
                # time, where this derivation cannot follow it —
                # skipping it silently could leave the cache placed
                # against the heads that write it.  Loud, like every
                # plan-validation failure in this module.
                raise ValueError(
                    f"serving plan rule ({pat.pattern!r}, <callable>) "
                    f"matches attention path {path!r}; the KV-cache "
                    "placement is derived from the attention rules, "
                    "which therefore must be concrete PartitionSpecs "
                    "— spell the attention rule out (callable rules "
                    "remain fine for every other path)")
            spec_t = tuple(spec)
            entries = (spec_t[head_dim]
                       if len(spec_t) > head_dim else None)
            for a in _axes_of(entries):
                n = int(mesh.shape[a])
                if n <= 1:
                    continue
                heads = int(getattr(cfg, attr))
                if heads % n:
                    raise ValueError(
                        f"serving plan rule ({pat.pattern!r}, "
                        f"{spec}) shards the head dimension of "
                        f"{path!r} over mesh axis {a!r} (size {n}), "
                        f"but {attr}={heads} is not divisible by it — "
                        "shrink the axis or pick a head count the "
                        "mesh can split")
                if axis is not None and a != axis:
                    raise ValueError(
                        f"serving plan shards attention heads over "
                        f"two different mesh axes ({axis!r} via "
                        f"{culprit!r}, {a!r} via {pat.pattern!r}); "
                        "the KV cache has ONE heads dimension — use "
                        "one axis")
                axis, culprit = a, pat.pattern
            break  # first-match-wins, like every plan lookup
    return axis


def kv_slab_specs(tree, axis: str | None):
    """PartitionSpecs for a KV cache / paged block slab / prefix-pool
    slab: the kv-heads dimension shards over ``axis``, everything else
    replicates.  Works on every KV layout in the repo because they all
    end ``[..., kv_heads, head_dim]`` for data leaves and
    ``[..., kv_heads]`` for the int8 scale leaves — the heads dim is
    ``ndim-2`` or ``ndim-1`` keyed on the leaf name.  ``axis=None``
    replicates everything (the pure-FSDP serving layout)."""
    def leaf(path, a):
        ndim = getattr(a, "ndim", len(a.shape))
        if axis is None:
            return P()
        hd = ndim - 1 if leaf_name(path).endswith("scale") else ndim - 2
        spec = [None] * (hd + 1)
        spec[hd] = axis
        return P(*spec)

    return jax.tree_util.tree_map_with_path(leaf, tree)


def kv_slab_shardings(mesh: Mesh, tree, axis: str | None):
    """:func:`kv_slab_specs` wrapped into ``NamedSharding`` — the form
    ``jax.device_put`` and ``with_sharding_constraint`` consume."""
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), kv_slab_specs(tree, axis))


__all__ = ["UnmatchedLeafError", "nearest_patterns", "leaf_name",
           "compile_rules",
           "first_match", "match_rules", "match_partition_rules",
           "tree_shardings", "shard_view_rule", "zero_state_rules",
           "zero_state_shardings", "zero3_param_shardings",
           "serving_kv_axis", "kv_slab_specs", "kv_slab_shardings"]
