from distkeras_tpu.parallel import collectives, rules
from distkeras_tpu.parallel.async_tier import (AsyncConfig, AsyncPlane,
                                                AsyncSchedule, VirtualClock)
from distkeras_tpu.parallel.collectives import (Zero1Layout, all_gather,
                                                 gather_bucket,
                                                 reduce_scatter,
                                                 zero1_optimizer)
from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh, local_device_count
from distkeras_tpu.parallel.rules import match_partition_rules, match_rules
from distkeras_tpu.parallel.sharding import (ShardingPlan, Zero1Plan,
                                              Zero3Plan, dp_plan, fsdp_plan,
                                              tp_plan, zero1_plan,
                                              zero3_plan)

__all__ = ["MeshSpec", "make_mesh", "local_device_count", "ShardingPlan",
           "dp_plan", "fsdp_plan", "tp_plan", "zero1_plan", "zero3_plan",
           "Zero1Plan", "Zero3Plan", "collectives", "rules", "Zero1Layout",
           "reduce_scatter", "all_gather", "gather_bucket",
           "zero1_optimizer", "match_partition_rules", "match_rules",
           "AsyncConfig", "AsyncPlane", "AsyncSchedule", "VirtualClock"]
