from distkeras_tpu.parallel import collectives
from distkeras_tpu.parallel.collectives import (Zero1Layout, all_gather,
                                                 reduce_scatter,
                                                 zero1_optimizer)
from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh, local_device_count
from distkeras_tpu.parallel.sharding import (ShardingPlan, Zero1Plan,
                                              dp_plan, fsdp_plan, tp_plan,
                                              zero1_plan)

__all__ = ["MeshSpec", "make_mesh", "local_device_count", "ShardingPlan",
           "dp_plan", "fsdp_plan", "tp_plan", "zero1_plan", "Zero1Plan",
           "collectives", "Zero1Layout", "reduce_scatter", "all_gather",
           "zero1_optimizer"]
