from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh, local_device_count
from distkeras_tpu.parallel.sharding import (ShardingPlan, dp_plan,
                                              fsdp_plan, tp_plan)

__all__ = ["MeshSpec", "make_mesh", "local_device_count", "ShardingPlan",
           "dp_plan", "fsdp_plan", "tp_plan"]
