from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh, local_device_count
from distkeras_tpu.parallel.sharding import ShardingPlan

__all__ = ["MeshSpec", "make_mesh", "local_device_count", "ShardingPlan"]
