"""Bounded-staleness async parameter-serving plane (docs/async.md).

The genuinely-asynchronous host tier the source paper's DOWNPOUR/AEASGD
family promises (reference: distkeras/parameter_servers.py workers
pushing pickled deltas over TCP): each host trains locally — any
intra-host ADAG/zero/exchange configuration, compiled to one XLA
program over the host mesh — and exchanges PARAMETER DELTAS with a
central plane asynchronously, under a bounded-staleness contract:

* **Staleness bound τ** (SSP): a host may start round ``r`` only while
  ``r - min(fleet rounds) <= tau``.  Past the bound a **hard-sync
  barrier** fires (``async.hard_sync`` event) — but only for a laggard
  that is *slow and alive*.  A laggard whose heartbeat went stale
  (wedged writer, dead host) is **evicted** by the watchdog instead
  (``async.evict``), so a straggler degrades the fleet by at most the
  detection window — never a full stall.  That asymmetry is the whole
  robustness story: sync SGD's step DAG freezes on one dead peer
  (arXiv:1805.03812); here the dead peer merely leaves.
* **Aggregation tree**: cross-host deltas reduce up an explicit
  ``fanout``-ary host-level aggregator tree (the in-network-aggregation
  shape, arXiv:1903.06701) rather than a flat ring, with
  Adasum (:func:`~distkeras_tpu.parallel.exchange.adasum_combine`) as
  the default merge rule — the mean for parallel contributions, the sum
  for orthogonal ones, which is exactly the taming stale deltas need.
* **Int8 error-feedback wire**: cross-host legs ride the exchange
  layer's symmetric int8 codec with a per-host residual carried to the
  next push (same EF contract as ``compress="int8"`` gradients);
  :func:`make_wire_merge` is the compiled spelling of one aggregation
  wave (encode → s8 all-gather → decode → tree combine) that the IR
  census audits, proving the wire carries s8, not f32.
* **Elastic membership**: hosts join mid-training (bootstrap params
  from the plane at the current version) and leave gracefully (final
  delta pushed before deregistration — the "refcounted" path) or
  ungracefully (eviction drops their in-flight deltas — the staleness
  rule path).  Membership transitions bump an
  :class:`~distkeras_tpu.resilience.cluster.EpochStore` generation and
  heartbeats are real ``health.write_beat`` files when a ``coord_dir``
  is given, so the plane rides the PR-5 cluster substrate.
* **Determinism**: every schedule runs under a seeded virtual-time
  clock (:class:`VirtualClock` + :class:`AsyncSchedule`); round
  durations, stalls, joins and leaves are pure functions of the seed,
  so any staleness interleaving — including evictions and joins — is
  replayable bit-for-bit in tests.

Chaos probe sites (resilience/chaos.py): ``cluster.push`` fires BEFORE
a host's delta publishes (a ``fail`` rule there is host-death mid-push:
nothing was enqueued, the delta drops cleanly) and ``cluster.merge``
fires BEFORE the root applies an aggregation wave (a fault leaves the
center params and the pending buffer intact — the merge is atomic and
simply retries on the next push).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from distkeras_tpu import obs
from distkeras_tpu.parallel.compat import shard_map
from distkeras_tpu.parallel.exchange import (adasum_combine, int8_decode,
                                              int8_encode)

_MERGE_RULES = ("adasum", "sum")
_COMPRESS = (None, "int8")


@dataclasses.dataclass(frozen=True)
class AsyncConfig:
    """Knobs of the async tier (validated at construction).

    ``tau`` is the staleness bound in rounds; ``beat_window`` the
    heartbeat-staleness window in *virtual* seconds — a parked fleet
    evicts a wedged laggard after at most this long, so choose it
    well under ``tau`` round-lengths to keep the <τ degradation bound.
    """

    tau: int = 4
    merge_rule: str = "adasum"
    compress: str | None = "int8"
    fanout: int = 2
    beat_window: float = 3.0

    def __post_init__(self):
        if self.tau < 1:
            raise ValueError(f"tau must be >= 1, got {self.tau}")
        if self.merge_rule not in _MERGE_RULES:
            raise ValueError(
                f"merge_rule must be one of {_MERGE_RULES}, "
                f"got {self.merge_rule!r}")
        if self.compress not in _COMPRESS:
            raise ValueError(
                f"compress must be one of {_COMPRESS}, "
                f"got {self.compress!r}")
        if self.fanout < 2:
            raise ValueError(f"fanout must be >= 2, got {self.fanout}")
        if self.beat_window <= 0:
            raise ValueError(
                f"beat_window must be > 0, got {self.beat_window}")


class VirtualClock:
    """Monotone virtual time: the one clock every schedule, heartbeat
    and staleness decision reads.  Advancing is the event loop's job;
    nothing in the plane ever reads wall time."""

    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def now(self) -> float:
        return self._now

    def advance_to(self, t: float) -> float:
        if t < self._now:
            raise ValueError(
                f"virtual time moved backwards: {t} < {self._now}")
        self._now = float(t)
        return self._now

    def __call__(self) -> float:  # health.write_beat clock= protocol
        return self._now


class AsyncSchedule:
    """Seeded, fully deterministic per-host round timing + membership
    events.  ``duration(host, rnd)`` is a pure function of
    ``(seed, host, rnd)`` (independent draws via ``SeedSequence``), so
    two runs of the same schedule produce the same interleaving.

    Fault/elasticity spellings (all return ``self`` for chaining):

    * ``stall(host, at_round, extra)`` — that round takes ``extra``
      additional virtual seconds AND the host's heartbeat wedges for
      the duration (the ``stall:cluster.heartbeat`` fault kind in
      virtual time).
    * ``join(host, at_time)`` — a new host joins the plane at ``t``.
    * ``leave(host, after_round)`` — graceful leave once the host
      completes that round (remaining data dropped).
    """

    def __init__(self, seed: int = 0, base: float = 1.0,
                 jitter: float = 0.25):
        if base <= 0:
            raise ValueError(f"base must be > 0, got {base}")
        if not 0 <= jitter < 1:
            raise ValueError(f"jitter must be in [0, 1), got {jitter}")
        self.seed = int(seed)
        self.base = float(base)
        self.jitter = float(jitter)
        self._stalls: dict[tuple[int, int], float] = {}
        self._joins: list[tuple[float, int]] = []
        self._leaves: dict[int, int] = {}

    def duration(self, host: int, rnd: int) -> float:
        rng = np.random.default_rng(
            np.random.SeedSequence([self.seed, int(host), int(rnd)]))
        d = self.base * (1.0 + self.jitter * (2.0 * rng.random() - 1.0))
        return d + self._stalls.get((host, rnd), 0.0)

    def stall(self, host: int, at_round: int,
              extra: float) -> "AsyncSchedule":
        if extra < 0:
            raise ValueError(f"extra must be >= 0, got {extra}")
        self._stalls[(int(host), int(at_round))] = float(extra)
        return self

    def stalled(self, host: int, rnd: int) -> bool:
        return (int(host), int(rnd)) in self._stalls

    def join(self, host: int, at_time: float) -> "AsyncSchedule":
        self._joins.append((float(at_time), int(host)))
        self._joins.sort()
        return self

    def joins(self) -> list[tuple[float, int]]:
        return list(self._joins)

    def leave_after(self, host: int) -> int | None:
        return self._leaves.get(int(host))

    def leave(self, host: int, after_round: int) -> "AsyncSchedule":
        self._leaves[int(host)] = int(after_round)
        return self


# --------------------------------------------------------- merge kernels


def _stack_leaves(trees: list) -> Any:
    """``m`` same-structure pytrees -> one pytree of ``[m, ...]`` leaves."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)


@jax.jit
def _combine_adasum(stacked):
    """One aggregator node: ``[m, ...]`` leaves -> merged leaves, per
    leaf by pairwise adaptive summation over the flattened vector."""
    def leaf(x):
        flat = x.reshape((x.shape[0], -1))
        return adasum_combine(flat).reshape(x.shape[1:])

    return jax.tree.map(leaf, stacked)


@jax.jit
def _combine_sum(stacked):
    """One aggregator node under ``merge_rule="sum"``: deltas SUM up
    the tree — the DOWNPOUR commit semantic (each host's delta is
    already scaled by its own learning rate; a mean would shrink the
    effective step as the fleet grows).  Adasum lands between the two:
    the mean for parallel deltas, this sum for orthogonal ones."""
    return jax.tree.map(lambda x: jnp.sum(x, axis=0), stacked)


def combine_group(deltas: list, merge_rule: str):
    """Merge one aggregator group's deltas (``len(deltas) <= fanout``)."""
    if len(deltas) == 1:
        return deltas[0]
    stacked = _stack_leaves(deltas)
    if merge_rule == "adasum":
        return _combine_adasum(stacked)
    return _combine_sum(stacked)


def tree_reduce(deltas: list, fanout: int, merge_rule: str):
    """Reduce ``m`` host deltas up the explicit ``fanout``-ary
    aggregator tree: level 0 merges groups of ``fanout`` hosts, each
    group's result rides up to the next tier, until one delta reaches
    the root.  Deterministic: tree shape depends only on ``m``."""
    while len(deltas) > 1:
        deltas = [combine_group(deltas[i:i + fanout], merge_rule)
                  for i in range(0, len(deltas), fanout)]
    return deltas[0]


@jax.jit
def _encode_ef(delta, residual):
    """Error-feedback int8 encode of a delta pytree: quantize
    ``delta + residual`` per-leaf (one row per leaf), return
    ``(q s8 leaves, scale leaves, decoded leaves, new residual)`` —
    the decoded tree is what crosses the (simulated) wire; the
    quantization error is carried to the NEXT push, same EF contract
    as the gradient codec (docs/lowcomm.md)."""
    def leaf(d, r):
        x = jnp.asarray(d, jnp.float32) + r
        q, scale = int8_encode(x.reshape(1, -1))
        dec = int8_decode(q, scale).reshape(d.shape)
        return q, scale, dec, x - dec

    out = jax.tree.map(leaf, delta, residual)
    unzip = lambda i: jax.tree.map(lambda t: t[i], out,
                                   is_leaf=lambda t: isinstance(t, tuple))
    return unzip(0), unzip(1), unzip(2), unzip(3)


@jax.jit
def delta_of(tv_new, tv_pulled):
    """``tv_new - tv_pulled`` without donating either operand."""
    return jax.tree.map(jnp.subtract, tv_new, tv_pulled)


@jax.jit
def apply_delta(center, delta):
    return jax.tree.map(jnp.add, center, delta)


def copy_tree(tree):
    """A real copy: the trainers donate their state buffers, so the
    center must never alias anything a jitted step consumes."""
    return jax.tree.map(lambda a: jnp.array(a, copy=True), tree)


def wire_cost_bytes(q_tree, scale_tree) -> int:
    """Ring-free accounting of one push's cross-host bytes: the s8
    payload plus its f32 per-row scales."""
    qb = sum(int(np.prod(q.shape)) for q in jax.tree.leaves(q_tree))
    sb = sum(int(np.prod(s.shape)) * 4
             for s in jax.tree.leaves(scale_tree))
    return qb + sb


def make_wire_merge(mesh, config: AsyncConfig) -> Callable:
    """The compiled spelling of ONE aggregation wave for the IR census:
    a shard_map over the mesh ``data`` axis (standing in for the host
    tier — one replica per host), where each replica int8-encodes its
    delta, the s8 payload and f32 scales are all-gathered (the only
    cross-host wire legs, and the census proves the payload dtype is
    s8), every aggregator decodes and tree-combines, and the merged
    delta comes back replicated.

    ``wire_merge(stacked_delta)`` with leaves ``[n_hosts, ...]``
    sharded ``P("data")`` -> merged delta leaves, replicated.
    """
    axis = "data"
    rule = config.merge_rule
    fanout = config.fanout
    compress = config.compress
    n = int(mesh.shape[axis])

    def body(stacked):
        def leaf(x):
            # x: [1, ...] — this replica's delta leaf.
            flat = x.reshape(1, -1).astype(jnp.float32)
            if compress == "int8":
                q, scale = int8_encode(flat)
                gq = jax.lax.all_gather(q, axis, axis=0)        # s8 wire
                gs = jax.lax.all_gather(scale, axis, axis=0)
                stack = int8_decode(gq, gs).reshape(n, -1)
            else:
                stack = jax.lax.all_gather(flat, axis,
                                           axis=0).reshape(n, -1)
            rows = [stack[i] for i in range(n)]
            merged = tree_reduce(rows, fanout, rule)
            return merged.reshape(x.shape[1:])

        return jax.tree.map(leaf, stacked)

    return shard_map(body, mesh=mesh,
                     in_specs=(P(axis),), out_specs=P(),
                     check_vma=False)


# ------------------------------------------------------------- the plane


@dataclasses.dataclass
class HostSlot:
    """Per-member bookkeeping: completed round, pulled center version,
    heartbeat freeze state, and the int8 EF residual."""

    round: int = 0
    version: int = 0
    joined_at: float = 0.0
    frozen_at: float | None = None   # wedged heartbeat since t (None = fresh)
    residual: Any = None
    beats: int = 0


class AsyncPlane:
    """The parameter-serving plane: center params + elastic membership
    + the aggregation tree, all under one virtual clock.

    Invariants the chaos legs assert:

    * ``push`` probes ``cluster.push`` BEFORE anything is enqueued — a
      fault there means the delta never existed (host death mid-push,
      dropped cleanly).
    * an aggregation wave probes ``cluster.merge`` BEFORE the center
      mutates — a fault there leaves center AND the pending buffer
      intact (``version`` does not advance; the wave retries on the
      next push).  No torn merge is representable.
    """

    def __init__(self, center, config: AsyncConfig, clock: VirtualClock,
                 coord_dir: str | None = None):
        self.config = config
        self.clock = clock
        self.center = copy_tree(center)
        self.version = 0
        self.members: dict[int, HostSlot] = {}
        self.pending: list[tuple[int, Any]] = []
        self.pushes = 0
        self.merges = 0
        self.hard_syncs = 0
        self.evicted: list[int] = []
        self.dropped_deltas = 0
        self.wire_bytes = 0
        self.epoch = 0
        self._store = None
        self._hb_dir = None
        if coord_dir is not None:
            import os

            from distkeras_tpu.resilience.cluster import EpochStore

            self._store = EpochStore(coord_dir)
            self._store.request(self.epoch)
            self._hb_dir = os.path.join(coord_dir, "beats")

    # ------------------------------------------------------- membership

    def _bump_epoch(self) -> None:
        """Every membership transition is a cluster-epoch generation —
        the same monotone marker-file contract coordinated restarts use
        (resilience/cluster.py), so an external supervisor can observe
        the async fleet's composition history."""
        self.epoch += 1
        if self._store is not None:
            self._store.request(self.epoch)

    def join(self, host: int):
        """Register ``host`` and bootstrap it: returns
        ``(params, version)`` copied from the center.  The joiner
        registers at the fleet's max round so it cannot trip the
        staleness bound the instant it arrives."""
        if host in self.members:
            raise ValueError(f"host {host} is already a member")
        rnd = max((m.round for m in self.members.values()), default=0)
        self.members[host] = HostSlot(
            round=rnd, version=self.version, joined_at=self.clock.now(),
            residual=jax.tree.map(
                lambda a: jnp.zeros_like(a, jnp.float32), self.center))
        self._bump_epoch()
        self.beat(host)
        obs.event("async.join", host=host, round=rnd,
                  version=self.version, t=self.clock.now())
        obs.gauge("async.members", len(self.members))
        return copy_tree(self.center), self.version

    def leave(self, host: int, final_delta=None) -> None:
        """Graceful deregistration.  A ``final_delta`` is pushed FIRST
        — the leaver's in-flight contribution is refcounted into the
        tree before the slot disappears — so a clean leave never loses
        work; only eviction (the staleness rule) drops deltas."""
        self._require_member(host)
        if final_delta is not None:
            self.push(host, final_delta)
        self._write_beat(host, done=True)
        del self.members[host]
        self._bump_epoch()
        obs.event("async.leave", host=host, t=self.clock.now())
        obs.gauge("async.members", len(self.members))

    def evict(self, host: int, reason: str) -> None:
        """Drop a member and every in-flight delta it owns (the
        bounded-staleness rule's discard path)."""
        self._require_member(host)
        before = len(self.pending)
        self.pending = [(h, d) for h, d in self.pending if h != host]
        self.dropped_deltas += before - len(self.pending)
        del self.members[host]
        self.evicted.append(host)
        self._bump_epoch()
        obs.event("async.evict", host=host, reason=reason,
                  dropped=before - len(self.pending), t=self.clock.now())
        obs.count("async.evictions", 1, reason=reason)
        obs.gauge("async.members", len(self.members))

    def _require_member(self, host: int) -> None:
        if host not in self.members:
            raise KeyError(f"host {host} is not a member "
                           f"(members: {sorted(self.members)})")

    # -------------------------------------------------------- heartbeats

    def _write_beat(self, host: int, done: bool = False) -> None:
        if self._hb_dir is not None:
            from distkeras_tpu.resilience.health import write_beat

            write_beat(self._hb_dir, host, self.epoch,
                       self.members[host].beats, clock=self.clock,
                       done=done)

    def beat(self, host: int) -> None:
        """One virtual-time heartbeat.  A frozen writer (stalled host)
        publishes nothing — that silence is what the watchdog reads."""
        m = self.members[host]
        if m.frozen_at is not None:
            return
        m.beats += 1
        self._write_beat(host)

    def freeze_beats(self, host: int) -> None:
        """The host's heartbeat writer wedges NOW (virtual time): the
        stall fault kind.  Peers see its last beat age out."""
        self._require_member(host)
        self.members[host].frozen_at = self.clock.now()

    def thaw_beats(self, host: int) -> None:
        if host in self.members:
            self.members[host].frozen_at = None
            self.beat(host)

    def stale(self, host: int) -> bool:
        """Heartbeat-driven straggler detection: stale means the writer
        froze more than ``beat_window`` virtual seconds ago.  A healthy
        member's daemon writer beats continuously, so it is never
        stale no matter how slow its rounds are — slow-but-alive gets
        the barrier, wedged-or-dead gets evicted."""
        m = self.members.get(host)
        if m is None:
            return True
        return (m.frozen_at is not None
                and self.clock.now() - m.frozen_at > self.config.beat_window)

    # ------------------------------------------------------ delta plane

    def pull(self, host: int):
        """Fresh center params for ``host`` (a real copy — trainers
        donate their buffers into the jitted step)."""
        self._require_member(host)
        self.members[host].version = self.version
        return copy_tree(self.center), self.version

    def push(self, host: int, delta) -> None:
        """Publish one host's parameter delta into the aggregation
        tree.  The ``cluster.push`` probe fires before anything is
        enqueued; int8 EF encoding happens on the way in (the wire
        leg), and the wave merges immediately — atomically — at the
        root."""
        from distkeras_tpu.resilience import chaos

        self._require_member(host)
        chaos.probe("cluster.push", step=self.pushes + 1)
        self.pushes += 1
        m = self.members[host]
        if self.config.compress == "int8":
            q, scale, decoded, m.residual = _encode_ef(delta, m.residual)
            cost = wire_cost_bytes(q, scale)
        else:
            decoded = jax.tree.map(
                lambda d: jnp.asarray(d, jnp.float32), delta)
            cost = sum(int(np.prod(x.shape)) * 4
                       for x in jax.tree.leaves(decoded))
        self.wire_bytes += cost
        obs.count("async.push", 1, host=host)
        obs.count("async.wire_bytes", cost, host=host)
        self.pending.append((host, decoded))
        self._merge_pending()

    def _merge_pending(self) -> None:
        """One aggregation wave: tree-combine every pending delta and
        apply the result to the center.  Probed, and atomic — a fault
        before the apply leaves center/version/pending untouched."""
        from distkeras_tpu.resilience import chaos

        if not self.pending:
            return
        try:
            chaos.probe("cluster.merge", step=self.merges + 1)
        except chaos.FaultInjected:
            obs.event("async.merge_fault", pending=len(self.pending),
                      t=self.clock.now())
            return  # wave retries at the next push; nothing torn
        merged = tree_reduce([d for _, d in self.pending],
                             self.config.fanout, self.config.merge_rule)
        self.center = apply_delta(self.center, merged)
        self.version += 1
        self.merges += 1
        self.pending = []
        obs.gauge("async.version", self.version)

    def flush(self) -> None:
        """Drain any aggregation wave a ``cluster.merge`` fault
        deferred (the retry path; a no-op when nothing is pending)."""
        self._merge_pending()

    def complete(self, host: int) -> int:
        """Mark one finished local round; returns the new round."""
        self._require_member(host)
        m = self.members[host]
        m.round += 1
        self.beat(host)
        obs.gauge("async.round", m.round, host=host)
        self._lag_gauges()
        return m.round

    # -------------------------------------------------------- staleness

    def min_round(self) -> int:
        return min((m.round for m in self.members.values()), default=0)

    def laggards(self, next_round: int) -> list[int]:
        """Members whose completed round would violate the bound if
        some host started ``next_round``."""
        return sorted(h for h, m in self.members.items()
                      if next_round - m.round > self.config.tau)

    def may_start(self, host: int,
                  next_round: int) -> tuple[bool, list[int]]:
        """The SSP gate: ``host`` may start ``next_round`` iff no peer
        is more than τ rounds behind it.  Blocked starts are the
        hard-sync barrier (counted + evented once per park)."""
        self._require_member(host)
        lag = [h for h in self.laggards(next_round) if h != host]
        if lag:
            self.hard_syncs += 1
            obs.event("async.hard_sync", host=host, round=next_round,
                      laggards=",".join(map(str, lag)),
                      t=self.clock.now())
            return False, lag
        return True, []

    def _lag_gauges(self) -> None:
        if not self.members:
            return
        lo = self.min_round()
        for h, m in self.members.items():
            obs.gauge("async.round_lag", m.round - lo, host=h)
        obs.gauge("async.staleness",
                  max(m.round for m in self.members.values()) - lo)


__all__ = ["AsyncConfig", "AsyncSchedule", "AsyncPlane", "VirtualClock",
           "HostSlot", "tree_reduce", "combine_group", "make_wire_merge",
           "delta_of", "apply_delta", "copy_tree", "wire_cost_bytes"]
