"""Bucketed cross-replica collectives + the ZeRO-1 sharded weight update.

The data-parallel trainers' gradient exchange is compiler-inserted: the
batch shards over the mesh ``data`` axis and XLA all-reduces the
gradient of the replicated parameters.  The *update* that consumes it,
though, was fully replicated — every replica holds the whole optimizer
state and redundantly computes the whole update each round, exactly the
waste "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" (arXiv 2004.13336) identifies.  This module is
that paper's construction for this codebase:

    reduce-scatter(grads)  ->  each replica updates only its 1/n shard
                           ->  all-gather(new update)

with *identical training math* (RS+AG moves exactly the bytes the old
all-reduce did; the update is elementwise, so sharding it changes
nothing) and ~n x less optimizer-state memory per device.

Mechanics.  Gradient pytrees are flattened into ~fixed-size **fusion
buckets**: each leaf is padded to a multiple of ``n`` (the ``data``
axis size) and viewed as ``[n, cols]`` — row ``k`` is the chunk replica
``k`` owns — then same-dtype leaves are concatenated along the column
axis until a bucket reaches ``bucket_mb``.  Per-bucket issuance (rather
than one monolithic exchange) is what lets the scheduler overlap bucket
``k``'s reduce-scatter with bucket ``k+1``'s packing and the unpacked
buckets' update math — the comm/compute overlap "A DAG Model of
Synchronous SGD" (arXiv 1805.03812) formalizes.  Because every leaf's
chunk boundary lies on the bucket's *row* boundary, slicing a leaf back
out of a scattered bucket is a column slice — no resharding, no
communication.

Two spellings of each collective:

* :func:`scatter` — the jit-native reduce-scatter: a sharding
  constraint to ``P(axis, None)``.  Fed a gradient whose all-reduce is
  still pending, GSPMD emits a reduce-scatter instead (the same
  mechanism that gives ``fsdp_plan`` its gradient reduce-scatters).
* :func:`reduce_scatter` / :func:`all_gather` — the explicit
  shard_map primitives (via ``parallel/compat.py``), for manual-SPMD
  callers and for testing the collective math in isolation.
  ``all_gather`` is also the hot path's parameter-update gather.

:func:`zero1_optimizer` wraps any *elementwise* optax transform (the
whole ``ops/optimizers.py`` name set; see
``ops.optimizers.zero1_compatible``) into the sharded update.  It is a
drop-in ``optax.GradientTransformation``, so every trainer that calls
``optimizer.update`` — the Keras accumulation step, LMTrainer's train
step, the EMA/clip chains — picks it up unchanged.

The bucketed layout here is also the substrate of the pluggable
**gradient-exchange layer** (``parallel/exchange.py``): Adasum merging,
local-SGD periodic sync, and error-feedback int8/top-k compression all
operate per fusion bucket, and the int8 codec composes with ZeRO-1 by
compressing exactly the reduce-scatter leg of this module's exchange
(docs/lowcomm.md).
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distkeras_tpu import obs
from distkeras_tpu.parallel.compat import shard_map

# ~4 MB buckets: big enough to amortize collective launch latency,
# small enough that several buckets pipeline inside one exchange.
DEFAULT_BUCKET_MB = 4.0


@dataclasses.dataclass(frozen=True)
class _Slot:
    """Where one pytree leaf lives inside the bucketed layout."""

    shape: tuple
    dtype: Any
    size: int       # prod(shape)
    cols: int       # padded size // n; the columns this leaf occupies
    bucket: int     # bucket index
    offset: int     # column offset inside the bucket


@dataclasses.dataclass(frozen=True)
class Zero1Layout:
    """Deterministic leaf -> bucket placement for one pytree geometry.

    Computed from shapes/dtypes only (works on arrays or
    ``ShapeDtypeStruct`` trees), so the optimizer wrapper can rebuild
    the identical layout at init and at every update trace.
    """

    n: int
    treedef: Any
    slots: tuple[_Slot, ...]         # in leaf order
    bucket_cols: tuple[int, ...]     # column count per bucket
    bucket_dtypes: tuple[Any, ...]
    # Per-bucket group key (all None without `groups=`): the exchange
    # layer's per-bucket codec choice buckets by (dtype, group) so a
    # bucket is always codec-homogeneous (parallel/exchange.py).
    bucket_groups: tuple[Any, ...] = ()

    @classmethod
    def for_tree(cls, tree, n: int,
                 bucket_mb: float = DEFAULT_BUCKET_MB,
                 groups=None) -> "Zero1Layout":
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        if n < 1:
            raise ValueError(f"axis size must be >= 1, got {n}")
        if groups is None:
            group_of = [None] * len(leaves)
        else:
            group_of = jax.tree_util.tree_leaves(
                groups, is_leaf=lambda x: x is None)
            if len(group_of) != len(leaves):
                raise ValueError(
                    f"groups carries {len(group_of)} entries for "
                    f"{len(leaves)} leaves")
        # Group by (dtype, group) — buckets concatenate, so they must
        # be dtype-homogeneous, and a group key (e.g. a codec) must
        # never straddle a bucket — then fill ~bucket_mb buckets in
        # leaf order.  With no groups this is exactly the historical
        # dtype-only bucketing, bit-for-bit.
        order = list(range(len(leaves)))
        by_key: dict[Any, list[int]] = {}
        for i in order:
            by_key.setdefault((np.dtype(leaves[i].dtype), group_of[i]),
                              []).append(i)
        slots: list[_Slot | None] = [None] * len(leaves)
        bucket_cols: list[int] = []
        bucket_dtypes: list[Any] = []
        bucket_groups: list[Any] = []
        for (dtype, group), idxs in by_key.items():
            budget = max(1, int(bucket_mb * 2 ** 20 / dtype.itemsize))
            cur_cols, cur_bucket = 0, -1
            for i in idxs:
                size = int(math.prod(leaves[i].shape)) or 1
                cols = -(-size // n)  # ceil: pad to a multiple of n
                if cur_bucket < 0 or cur_cols * n + cols * n > budget:
                    bucket_cols.append(0)
                    bucket_dtypes.append(dtype)
                    bucket_groups.append(group)
                    cur_bucket = len(bucket_cols) - 1
                    cur_cols = 0
                slots[i] = _Slot(shape=tuple(leaves[i].shape), dtype=dtype,
                                 size=int(math.prod(leaves[i].shape)),
                                 cols=cols, bucket=cur_bucket,
                                 offset=cur_cols)
                cur_cols += cols
                bucket_cols[cur_bucket] = cur_cols
        return cls(n=n, treedef=treedef, slots=tuple(slots),
                   bucket_cols=tuple(bucket_cols),
                   bucket_dtypes=tuple(bucket_dtypes),
                   bucket_groups=tuple(bucket_groups))

    # ------------------------------------------------------------ views

    @property
    def shard_shapes(self) -> frozenset:
        """Every ``[n, cols]`` shard-view shape in this layout — the
        shapes optimizer-state leaves take under ZeRO-1 (the trainers'
        sharding rules key on membership here)."""
        return frozenset((self.n, s.cols) for s in self.slots)

    def _leaf_view(self, slot: _Slot, x):
        """One leaf -> its ``[n, cols]`` chunk-major view (pad with 0)."""
        flat = jnp.reshape(x, (-1,))
        pad = slot.cols * self.n - slot.size
        if pad:
            flat = jnp.concatenate(
                [flat, jnp.zeros((pad,), dtype=flat.dtype)])
        return jnp.reshape(flat, (self.n, slot.cols))

    def shard_views(self, tree):
        """Pytree of original leaves -> same-structure pytree of
        ``[n, cols]`` views (row k = replica k's chunk).  Pure
        reshape/pad — no communication."""
        leaves = self.treedef.flatten_up_to(tree)
        return self.treedef.unflatten(
            [self._leaf_view(s, x) for s, x in zip(self.slots, leaves)])

    def unview(self, view_tree):
        """Inverse of :meth:`shard_views`: ``[n, cols]`` leaves back to
        their original shapes (drop the pad).  Used to read state that
        lives as shard views — e.g. the EMA shadow — back out in
        parameter layout; gathers if the views are sharded."""
        views = self.treedef.flatten_up_to(view_tree)
        return self.treedef.unflatten(
            [jnp.reshape(jnp.reshape(v, (-1,))[:s.size], s.shape)
             for s, v in zip(self.slots, views)])

    # ---------------------------------------------------------- buckets

    def pack(self, tree) -> list:
        """Pytree -> list of ``[n, C_b]`` fusion buckets."""
        return self.pack_views(self.shard_views(tree))

    def pack_views(self, view_tree) -> list:
        """Shard-view pytree (``[n, cols]`` leaves) -> bucket list.
        Column concatenation only: a sharded view stays sharded."""
        views = self.treedef.flatten_up_to(view_tree)
        groups: list[list] = [[] for _ in self.bucket_cols]
        for slot, v in zip(self.slots, views):
            groups[slot.bucket].append(v)
        return [vs[0] if len(vs) == 1 else jnp.concatenate(vs, axis=1)
                for vs in groups]

    def views_from_buckets(self, buckets: Sequence):
        """Bucket list -> shard-view pytree.  Column slices only (leaf
        boundaries sit on row boundaries by construction), so a
        scattered bucket yields scattered views with no resharding."""
        views = [buckets[s.bucket][:, s.offset:s.offset + s.cols]
                 for s in self.slots]
        return self.treedef.unflatten(views)

    def zero_buckets(self) -> list:
        """Fresh all-zero buckets in this layout — the ZeRO-2/3 step
        builders' gradient accumulator carry (kept scattered by a
        :func:`scatter` constraint per microbatch add)."""
        return [jnp.zeros((self.n, c), d)
                for c, d in zip(self.bucket_cols, self.bucket_dtypes)]

    def unpack(self, buckets: Sequence):
        """Bucket list -> pytree of original leaf shapes (drop pad)."""
        out = []
        for s in self.slots:
            flat = jnp.reshape(
                buckets[s.bucket][:, s.offset:s.offset + s.cols], (-1,))
            out.append(jnp.reshape(flat[:s.size], s.shape))
        return self.treedef.unflatten(out)


# ------------------------------------------------------------ collectives


def scatter(x, mesh: Mesh, axis: str = "data"):
    """Jit-native reduce-scatter of a ``[n, C]`` bucket: constrain it to
    ``P(axis, None)`` so replica ``k`` materializes only row ``k``.

    Fed a value whose cross-replica reduction is still pending (a
    gradient of replicated params over a data-sharded batch), GSPMD
    emits a reduce-scatter — the all-reduce never happens.  Fed an
    already-replicated value, it is a free local slice.  Outside a
    trace it is the identity (eager callers place state via
    ``device_put`` with the plan's shardings).
    """
    if isinstance(x, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(axis, None)))
    return x


def reduce_scatter(x, mesh: Mesh, axis: str = "data"):
    """Explicit reduce-scatter primitive (shard_map + ``psum_scatter``).

    ``x``: ``[n, C]`` whose *rows are per-replica addends* (e.g. stacked
    partial gradients), ``n`` = the ``axis`` size and ``C`` divisible
    by ``n`` (the scattered output gives each replica a ``C/n`` chunk).
    Returns the global ``[C]`` row-sum, sharded over ``axis`` (replica
    ``k`` holds columns ``[k*C/n, (k+1)*C/n)``).

    NOTE the contract difference from :func:`scatter`: here rows are
    independent contributions to a sum; there the input is one logical
    value whose rows are chunks.  The trainers' hot path uses
    :func:`scatter` (the gradient is one logical value under jit); this
    primitive serves manual-SPMD code and validates the collective math
    in isolation.
    """
    n = int(mesh.shape[axis])
    if x.ndim != 2 or x.shape[0] != n or x.shape[1] % n:
        raise ValueError(
            f"reduce_scatter takes [n, C] with n == the {axis!r} axis "
            f"size ({n}) and C divisible by n (each replica receives a "
            f"C/n chunk); got shape {tuple(x.shape)} — pad the columns "
            "to a multiple of the axis size")

    def body(s):  # [1, C] — this replica's addend
        return jax.lax.psum_scatter(s[0], axis, scatter_dimension=0,
                                    tiled=True)

    return shard_map(body, mesh=mesh, in_specs=P(axis, None),
                     out_specs=P(axis), check_vma=False)(x)


def all_gather(x, mesh: Mesh, axis: str = "data"):
    """Explicit all-gather primitive (shard_map): ``[n, C]`` sharded
    over ``axis`` on dim 0 -> the same value replicated on every
    replica.  The ZeRO-1 step's parameter-update gather."""
    def body(s):  # [1, C] — this replica's chunk
        return jax.lax.all_gather(s, axis, axis=0, tiled=True)

    return shard_map(body, mesh=mesh, in_specs=P(axis, None),
                     out_specs=P(None, None), check_vma=False)(x)


def _replicate(x, mesh: Mesh):
    """Jit-native all-gather of a scattered ``[n, C]`` bucket: constrain
    it to replicated so GSPMD materializes every row on every replica.
    Outside a trace it is the identity (eager sharded arrays gather on
    read)."""
    if isinstance(x, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(None, None)))
    return x


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def gather_bucket(x, mesh: Mesh, axis: str = "data"):
    """The ZeRO-3 gather-on-use primitive: forward re-materializes a
    scattered ``[n, C]`` parameter bucket on every replica (an
    all-gather under GSPMD), and the BACKWARD scatters the cotangent
    back to ``P(axis, None)`` — a reduce-scatter of the gradient, one
    per fusion bucket.

    The custom vjp is the point: ``with_sharding_constraint``'s own
    transpose would pin the cotangent replicated (forcing a full
    gradient all-reduce and a replicated gradient buffer); here the
    gradient of a gathered parameter only ever materializes as the
    ``1/n`` shard each replica owns.  Scopes ``zero3/param_gather`` /
    ``zero3/grad_scatter`` tag both legs for the declared-exchange
    parity proof (analysis/ir_lint.py) and profiler timelines.
    """
    with jax.named_scope("zero3/param_gather"):
        return _replicate(x, mesh)


def _gather_bucket_fwd(x, mesh, axis):
    with jax.named_scope("zero3/param_gather"):
        return _replicate(x, mesh), None


def _gather_bucket_bwd(mesh, axis, _, ct):
    with jax.named_scope("zero3/grad_scatter"):
        return (scatter(ct, mesh, axis),)


gather_bucket.defvjp(_gather_bucket_fwd, _gather_bucket_bwd)


def adasum_reduce(x, mesh: Mesh, axis: str = "data"):
    """Adasum merge primitive (shard_map): ``[n, C]`` whose *rows are
    per-replica addends* (the :func:`reduce_scatter` contract) ->
    their pairwise adaptive sum ``[C]``, replicated on every replica
    (arXiv 2006.02924; rule in ``parallel/exchange.py``).

    The standalone spelling of the bucketed exchange layer's
    ``merge_rule="adasum"`` for manual-SPMD callers and for testing
    the merge math in isolation: identical replicas reproduce the
    value itself (== mean-reduce of agreeing replicas), orthogonal
    replicas reproduce the plain sum.
    """
    from distkeras_tpu.parallel.exchange import adasum_combine

    n = int(mesh.shape[axis])
    if x.ndim != 2 or x.shape[0] != n:
        raise ValueError(
            f"adasum_reduce takes [n, C] with n == the {axis!r} axis "
            f"size ({n}); got shape {tuple(x.shape)}")

    def body(s):  # [1, C] — this replica's addend
        stacked = jax.lax.all_gather(s[0], axis, axis=0)  # [n, C]
        return adasum_combine(stacked).astype(s.dtype)

    return shard_map(body, mesh=mesh, in_specs=P(axis, None),
                     out_specs=P(None), check_vma=False)(x)


# ------------------------------------------------------------ the wrapper


def zero_validate(mesh: Mesh, spec, axis: str = "data",
                  stage: int = 1) -> None:
    """The ZeRO enablement checks, run at TRAINER CONSTRUCTION for
    every stage (1/2/3) by both trainer families, and by the exchange
    layer's zero1+int8 composition (``parallel/exchange.py``):

    * pure-``axis`` mesh — every stage here shards the update (and, at
      stage 3, the parameters) of an otherwise *replicated* layout;
    * an optimizer whose update rule is per-leaf elementwise
      (``ops.optimizers.zero1_compatible``).  A known-unsafe transform
      raises HERE, naming the offending optax transform (e.g.
      ``scale_by_trust_ratio`` inside a LAMB chain), instead of
      training to silently-diverged weights inside the scattered
      update; an uninspectable transform warns.
    """
    knob = f"zero={stage}" if stage != 1 else "zero1=True"
    for ax, size in mesh.shape.items():
        if ax != axis and int(size) > 1:
            raise ValueError(
                f"{knob} composes with the {axis} axis only, but the "
                f"mesh has {ax}={int(size)}; the ZeRO stages shard the "
                "update of *replicated* parameters — use fsdp/TP plans "
                "when a rule-driven parameter layout is wanted instead")
    from distkeras_tpu.ops.optimizers import (zero1_compatible,
                                              zero1_offender)

    compat = zero1_compatible(spec)
    if compat is False:
        offender = zero1_offender(spec)
        raise ValueError(
            f"optimizer {spec!r} is known-incompatible with the ZeRO "
            "sharded update"
            + (f": transform {offender!r} mixes elements within a leaf"
               if offender else
               " (its update rule mixes elements within a leaf)")
            + ", so sharding changes the math; train it replicated or "
            "under fsdp")
    if compat is None:
        import warnings

        warnings.warn(
            f"{knob} with a prebuilt/factory optax optimizer that "
            "cannot be verified elementwise: the sharded update is "
            "math-identical only for per-leaf elementwise update rules; "
            "transforms mixing elements within a leaf (LARS/LAMB trust "
            "ratios, Shampoo preconditioners) will silently diverge",
            stacklevel=3)


def zero1_validate(mesh: Mesh, spec, axis: str = "data") -> None:
    """Stage-1 spelling of :func:`zero_validate` (kept: the exchange
    layer and older call sites name it)."""
    zero_validate(mesh, spec, axis=axis, stage=1)


def zero1_optimizer(inner: optax.GradientTransformation, mesh: Mesh,
                    axis: str = "data",
                    bucket_mb: float = DEFAULT_BUCKET_MB
                    ) -> optax.GradientTransformation:
    """ZeRO-1 wrap of an elementwise optax transform.

    ``init`` builds the inner state over *shard views* (``[n, cols]``
    per leaf) — same pytree structure as the params, so path-keyed
    masks (weight-decay exclusions, LoRA masks) see the tree they
    expect — and the trainers place those leaves ``P(axis, None)``:
    each device persists 1/n of every moment buffer.

    ``update``:

    1. pack grads into fusion buckets, :func:`scatter` each —
       per-bucket reduce-scatter, issued as the buckets are packed;
    2. run ``inner.update`` on the scattered shard views (elementwise
       math partitions with zero communication; a chained
       ``clip_by_global_norm`` stays exact — its sum-of-squares over
       sharded leaves becomes a cheap scalar psum);
    3. pack the update shards back into buckets and :func:`all_gather`
       each; unpack to the original leaf shapes.

    Returned updates are replicated, so the caller's ``p + u`` is the
    replicated-path value bit-for-bit (modulo reduction order inside
    the collective).  Correctness requires the inner update to be
    elementwise per leaf — true of every named optimizer this package
    resolves (``ops.optimizers.zero1_compatible``); transforms that mix
    elements *within* a leaf (per-layer trust ratios a la LARS/LAMB)
    would silently change math and must not be wrapped.
    """
    n = int(mesh.shape[axis])

    def init(params):
        layout = Zero1Layout.for_tree(params, n, bucket_mb)
        return inner.init(layout.shard_views(params))

    def _record_layout(layout: Zero1Layout) -> None:
        """Bucket geometry into the obs metrics registry — runs at
        TRACE time (once per compile), so the per-step hot path is
        untouched.  Per-step *device-side* RS/AG timings are by design
        not host-observable (overlap interleaves them on the
        timeline); the ``jax.named_scope`` zero1 regions tag them on
        profiler traces, and these gauges size the exchange exactly."""
        if obs.active() is None:
            return
        bucket_bytes = [c * layout.n * np.dtype(d).itemsize
                        for c, d in zip(layout.bucket_cols,
                                        layout.bucket_dtypes)]
        pad = sum((s.cols * layout.n - s.size)
                  * np.dtype(s.dtype).itemsize for s in layout.slots)
        obs.gauge("zero1.buckets", len(bucket_bytes))
        obs.gauge("zero1.exchange_bytes", sum(bucket_bytes))
        obs.gauge("zero1.pad_bytes", pad)
        for b in bucket_bytes:
            obs.observe("zero1.bucket_bytes", b,
                        buckets=(2**18, 2**20, 2**22, 2**24, 2**26))

    def update(grads, state, params=None, **kw):
        layout = Zero1Layout.for_tree(grads, n, bucket_mb)
        _record_layout(layout)
        with jax.named_scope("zero1/reduce_scatter"):
            g_buckets = [scatter(b, mesh, axis) for b in layout.pack(grads)]
        g_views = layout.views_from_buckets(g_buckets)
        p_views = (None if params is None
                   else layout.shard_views(params))
        with jax.named_scope("zero1/update"):
            u_views, new_state = inner.update(g_views, state, p_views, **kw)
        with jax.named_scope("zero1/all_gather"):
            u_buckets = [all_gather(b, mesh, axis)
                         for b in layout.pack_views(u_views)]
        return layout.unpack(u_buckets), new_state

    return optax.GradientTransformation(init, update)


def zero1_enable(inner: optax.GradientTransformation, mesh: Mesh,
                 spec=None, bucket_mb: float | None = None,
                 axis: str = "data",
                 stage: int = 1) -> optax.GradientTransformation:
    """Validate a trainer's ZeRO configuration and return the wrapped
    optimizer — the ONE enablement path both trainer families share
    for every stage that wraps (``DistributedTrainer`` stages 1/2/3 —
    stages 2/3 consume only the wrapper's shard-view ``init`` and
    drive the raw inner from the step — and ``LMTrainer`` stage 1;
    LMTrainer stages 2/3 init over views directly and call
    :func:`zero_validate` alone).

    * Rejects meshes with any non-``axis`` dimension > 1: the ZeRO
      stages shard the update/state of *replicated* parameter layouts;
      rule-driven sharded-parameter layouts belong to fsdp/TP plans.
    * Checks ``spec`` (the user's optimizer spec, a name string or a
      prebuilt transform) against ``ops.optimizers.zero1_compatible``:
      known-unsafe raises naming the offending transform,
      uninspectable warns.
    """
    zero_validate(mesh, spec if spec is not None else inner, axis=axis,
                  stage=stage)
    return zero1_optimizer(
        inner, mesh, axis=axis,
        bucket_mb=DEFAULT_BUCKET_MB if bucket_mb is None else bucket_mb)


def zero1_shard_shapes(params, n: int) -> frozenset:
    """The ``[n, cols]`` shapes ZeRO-1 optimizer-state leaves take for
    this parameter tree — what :func:`zero1_state_shardings` matches
    against."""
    return Zero1Layout.for_tree(params, n).shard_shapes


def zero1_state_shardings(params, opt_state, mesh: Mesh,
                          axis: str = "data"):
    """Sharding tree for a ZeRO optimizer state (every stage): leaves
    whose shape is one of ``params``' shard-view shapes go
    ``P(axis, None)``; everything else replicates.

    The rule is by *shape*, structure-agnostic on purpose: it covers
    moments nested inside chains, masks, and EMA shadows uniformly —
    under a sharded update the inner optimizer only ever sees shard
    views, so every params-mirroring leaf it creates has a shard-view
    shape, and the remaining leaves are scalar counts.  Since the
    ZeRO-2/3 round it is expressed through the ONE regex rule engine
    (``parallel/rules.py``: the shape-keyed :func:`~distkeras_tpu.
    parallel.rules.shard_view_rule` ahead of a replicate-everything
    catch-all), the same ordered-rules form every other plan takes.
    ``opt_state`` may be real arrays or an ``eval_shape`` tree.
    """
    from distkeras_tpu.parallel.rules import zero_state_shardings

    return zero_state_shardings(params, opt_state, mesh, axis=axis)


__all__ = ["Zero1Layout", "scatter", "reduce_scatter", "all_gather",
           "gather_bucket", "adasum_reduce", "zero1_optimizer",
           "zero1_enable", "zero1_validate", "zero_validate",
           "zero1_shard_shapes", "zero1_state_shardings",
           "DEFAULT_BUCKET_MB"]
