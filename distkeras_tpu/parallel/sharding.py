"""Sharding plans: variable-path rules -> PartitionSpecs.

The reference has exactly one placement policy: the full weight vector
lives on the parameter server and full copies live on every worker
(distkeras/parameter_servers.py holds the "center variable").  Here
placement is a first-class, declarative plan: regex rules over Keras
variable paths map each parameter to a ``PartitionSpec`` on the mesh.
The default plan is pure data parallelism (weights replicated, batch
split over ``data``); a tensor-parallel plan shards the big matmul
operands over ``model`` and XLA inserts the all-gathers/reduce-scatters.
"""

from __future__ import annotations


from typing import Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _augment_fsdp(spec: P, shape, axis_size: int, axis: str) -> P:
    """Add ``axis`` to the largest still-unsharded dimension of ``shape``
    that divides evenly; leave small/indivisible params replicated.

    This is the ZeRO-3 placement rule expressed as sharding: parameters
    (and, via :meth:`ShardingPlan.state_shardings`, their optimizer-state
    mirrors) live scattered over the data axis, and GSPMD materializes
    them with an all-gather at use and a reduce-scatter on the gradient
    — the XLA-native form of FSDP, no hand-written collectives.
    """
    if axis_size <= 1 or shape is None:
        return spec
    spec_t = tuple(spec) + (None,) * (len(shape) - len(spec))
    used = set()
    for s in spec_t:
        for a in (s if isinstance(s, tuple) else (s,)):
            if a is not None:
                used.add(a)
    if axis in used:
        return spec
    best, best_size = None, 0
    for i, (dim, s) in enumerate(zip(shape, spec_t)):
        if s is None and dim % axis_size == 0 and dim > best_size:
            best, best_size = i, dim
    if best is None:
        return spec
    new = list(spec_t)
    new[best] = axis
    while new and new[-1] is None:
        new.pop()
    return P(*new)


class ShardingPlan:
    """Ordered (regex, PartitionSpec) rules; first match wins.

    Unmatched variables are replicated.  Rules match against the Keras
    variable path (e.g. ``"dense_1/kernel"``).

    ``fsdp_axis`` layers fully-sharded data parallelism on top of the
    rule-derived spec: each parameter's largest still-free dimension is
    sharded over that mesh axis (see :func:`_augment_fsdp`).  Rules and
    FSDP compose — a Megatron-TP rule can claim one dimension and FSDP
    takes another.
    """

    def __init__(self, rules: Sequence[tuple[str, P]] = (),
                 batch_spec: P = P("data"), fsdp_axis: str | None = None):
        from distkeras_tpu.parallel.rules import compile_rules

        self.rules = compile_rules(rules)
        self.batch_spec = batch_spec
        self.fsdp_axis = fsdp_axis

    def spec_for(self, path: str, shape=None, mesh: Mesh | None = None) -> P:
        # First-match-wins through the shared rule engine
        # (parallel/rules.py); a plan's unmatched leaves replicate —
        # the historical ShardingPlan default (rule authors who want
        # unmatched-leaf errors use rules.match_partition_rules).
        from distkeras_tpu.parallel.rules import first_match

        matched, spec = first_match(self.rules, path)
        if not matched:
            spec = P()
        if self.fsdp_axis is not None and mesh is not None:
            spec = _augment_fsdp(spec, shape,
                                 int(mesh.shape[self.fsdp_axis]),
                                 self.fsdp_axis)
        return spec

    # ------------------------------------------------------------- builders

    def param_shardings(self, mesh: Mesh, paths: Sequence[str],
                        shapes: Sequence | None = None):
        """NamedShardings for a list-of-arrays pytree ordered like ``paths``."""
        shapes = shapes if shapes is not None else [None] * len(paths)
        return [NamedSharding(mesh, self.spec_for(p, shape=s, mesh=mesh))
                for p, s in zip(paths, shapes)]

    def state_shardings(self, mesh: Mesh, state, tv_paths: Sequence[str]):
        """Shardings pytree matching a :class:`TrainState`.

        ``tv`` (and its optimizer-state mirrors) get the plan's rules;
        ``ntv``/``step`` are replicated.  Optax states are pytrees whose
        array leaves mirror parameter shapes (mu/nu in adam etc.) or are
        scalars; we map any leaf whose shape matches a param positionally.
        """
        tv_sh = self.param_shardings(
            mesh, tv_paths, [tuple(v.shape) for v in state.tv])
        rep = NamedSharding(mesh, P())

        # Optax states embed subtrees mirroring the param pytree (our tv
        # is a flat list, so e.g. adam's mu/nu are lists in tv order).
        # Match each opt-state leaf to its param by the *index* of the
        # innermost list it sits in — positional, not shape-based, so
        # same-shaped params with different specs stay distinct.  A leaf
        # whose innermost-list index doesn't correspond to a matching
        # param shape (EmptyState internals, scalar counts) replicates.
        tv_list = list(state.tv)

        def opt_leaf_sharding(path, leaf):
            idx = None
            for key in reversed(path):
                if isinstance(key, jax.tree_util.SequenceKey):
                    idx = key.idx
                    break
            if (idx is not None and idx < len(tv_list)
                    and hasattr(leaf, "shape")
                    and tuple(leaf.shape) == tuple(tv_list[idx].shape)):
                return tv_sh[idx]
            return rep

        from distkeras_tpu.models.adapter import TrainState

        return TrainState(
            tv=tv_sh,
            ntv=jax.tree.map(lambda _: rep, state.ntv),
            opt_state=jax.tree_util.tree_map_with_path(
                opt_leaf_sharding, state.opt_state),
            step=rep,
        )

    def batch_sharding(self, mesh: Mesh) -> NamedSharding:
        return NamedSharding(mesh, self.batch_spec)

    def tree_shardings(self, mesh: Mesh, pytree):
        """NamedShardings for any pytree, rules keyed on jax key-paths.

        Paths are rendered like ``"layers/0/attn/wq"`` (keystr with the
        leading separator stripped), so the same regex rule language
        covers Keras variable paths and functional-model dicts.
        """
        def leaf(path, x):
            from distkeras_tpu.parallel.compat import keystr

            name = keystr(path, simple=True, separator="/")
            shape = tuple(x.shape) if hasattr(x, "shape") else None
            return NamedSharding(mesh, self.spec_for(name, shape=shape,
                                                     mesh=mesh))

        return jax.tree_util.tree_map_with_path(leaf, pytree)


class Zero1Plan(ShardingPlan):
    """Data parallelism with the *optimizer state* sharded over ``data``
    (ZeRO-1): parameters replicate exactly like :func:`dp_plan` — the
    forward/backward is untouched — but every optimizer-state leaf that
    mirrors a parameter lives as a ``[n, cols]`` shard view (see
    ``parallel/collectives.py``) placed ``P("data", None)``, so each
    device persists 1/n of the moments.  Pair with
    ``collectives.zero1_optimizer``, which produces state in exactly
    that layout; the trainers wire both through ``zero1=True``.
    """

    def __init__(self, bucket_mb: float | None = None):
        super().__init__(rules=(), batch_spec=P("data"))
        from distkeras_tpu.parallel.collectives import DEFAULT_BUCKET_MB

        self.zero1 = True
        self.bucket_mb = (DEFAULT_BUCKET_MB if bucket_mb is None
                          else bucket_mb)

    def state_shardings(self, mesh: Mesh, state, tv_paths: Sequence[str]):
        """TrainState shardings: ``tv``/``ntv``/``step`` replicated;
        optimizer-state leaves take the ZeRO-1 shard-view rule (the
        shared ``collectives.zero1_state_shardings``)."""
        from distkeras_tpu.models.adapter import TrainState
        from distkeras_tpu.parallel.collectives import (
            zero1_state_shardings)

        rep = NamedSharding(mesh, P())
        return TrainState(
            tv=[rep for _ in state.tv],
            ntv=jax.tree.map(lambda _: rep, state.ntv),
            opt_state=zero1_state_shardings(list(state.tv),
                                            state.opt_state, mesh),
            step=rep,
        )


class ExchangePlan(ShardingPlan):
    """Data parallelism under a non-default gradient-exchange policy
    (``parallel/exchange.py``): parameters replicate like
    :func:`dp_plan`, but the optimizer state may carry error-feedback
    residuals (sharded over their leading replica axis) and — when the
    int8 codec composes with ZeRO-1 — scattered ``[n, cols]`` shard
    views.  One shared sharding rule
    (``exchange.exchange_state_shardings``) covers both.
    """

    def __init__(self, config, zero1: bool = False):
        super().__init__(rules=(), batch_spec=P("data"))
        self.exchange = config
        self.zero1 = zero1
        self.bucket_mb = config.bucket_mb

    def state_shardings(self, mesh: Mesh, state, tv_paths: Sequence[str]):
        from distkeras_tpu.models.adapter import TrainState
        from distkeras_tpu.parallel.exchange import (
            exchange_state_shardings)

        rep = NamedSharding(mesh, P())
        return TrainState(
            tv=[rep for _ in state.tv],
            ntv=jax.tree.map(lambda _: rep, state.ntv),
            opt_state=exchange_state_shardings(
                list(state.tv), state.opt_state, mesh,
                zero1=self.zero1),
            step=rep,
        )


class Zero3Plan(ShardingPlan):
    """Data parallelism with parameters AND optimizer state scattered
    as ``[n, cols]`` chunk-major shard views over ``data`` (ZeRO-3,
    gather-on-use): persistent state holds 1/n of every parameter,
    gradient-moment and EMA leaf per device; the train step
    re-materializes parameters per fusion bucket just-in-time
    (``collectives.gather_bucket``) and runs the update entirely on the
    shard views — no per-step parameter all-gather of the update.

    Unlike :func:`fsdp_plan` (the GSPMD dimension-sharding spelling of
    ZeRO-3), the chunk-major layout shards EVERY leaf regardless of
    divisibility (biases, norm scales — anything `_augment_fsdp` would
    leave replicated), and the gather is bucket-granular: a handful of
    fused all-gathers per step instead of one per parameter.  Derived
    from the shared rule engine (``parallel/rules.py``): the shape-
    keyed shard-view rule ahead of a replicate catch-all.
    """

    def __init__(self, bucket_mb: float | None = None):
        super().__init__(rules=(), batch_spec=P("data"))
        from distkeras_tpu.parallel.collectives import DEFAULT_BUCKET_MB

        self.zero = 3
        self.bucket_mb = (DEFAULT_BUCKET_MB if bucket_mb is None
                          else bucket_mb)

    def state_shardings(self, mesh: Mesh, state, tv_paths: Sequence[str]):
        """TrainState shardings for a state whose ``tv`` leaves are
        shard views: ``tv`` and the view-mirroring optimizer leaves
        scatter ``P("data", None)``; ``ntv``/``step``/scalar counts
        replicate — one ordered rule list (parallel/rules.py)."""
        from distkeras_tpu.models.adapter import TrainState
        from distkeras_tpu.parallel.rules import (zero3_param_shardings,
                                                  zero_state_shardings)

        rep = NamedSharding(mesh, P())
        return TrainState(
            tv=zero3_param_shardings(list(state.tv), mesh),
            ntv=jax.tree.map(lambda _: rep, state.ntv),
            opt_state=zero_state_shardings(list(state.tv),
                                           state.opt_state, mesh),
            step=rep,
        )


class ServingPlan(ShardingPlan):
    """A :class:`ShardingPlan` for the SERVE path (round 14): regex
    rules over the functional transformer's param paths place the
    parameters, and the KV cache / paged block slab / prefix-pool slab
    placement is DERIVED from them (``parallel/rules.py``'s
    ``serving_kv_axis``/``kv_slab_specs``) — the rule that shards
    attention heads over a mesh axis is what shards the cache's
    kv-heads dimension, so plan and cache can never disagree.

    Lane/row metadata (positions, current tokens, PRNG keys, page
    tables) always replicates: it is O(lanes) host bookkeeping, and
    replicating it keeps the admission scatters collective-free.

    Built by :func:`serving_plan`; consumed by
    ``ContinuousBatcher(plan=..., mesh=...)`` and
    ``PagedBatcher(plan=..., mesh=...)`` — which derive the KV axis
    through ``rules.serving_kv_axis`` (the ONE entry point; it works
    on any ShardingPlan, so this class adds no method for it).
    """


def _override_rules(extra_rules, stock_rules) -> list:
    """Compose user overrides ahead of stock rules, first-match-wins.

    An extra rule that spells a stock pattern VERBATIM replaces it —
    the stock copy is dropped rather than left as an unreachable
    duplicate, which ``rules.compile_rules`` (round 17) rejects at
    build time.  Overrides via broader/narrower patterns compose by
    ordering alone, as before.
    """
    seen = {pat for pat, _ in extra_rules}
    return list(extra_rules) + [(pat, val) for pat, val in stock_rules
                                if pat not in seen]


def serving_plan(extra_rules: Sequence[tuple[str, P]] = (),
                 fsdp_axis: str | None = None) -> ServingPlan:
    """The pod-sharded serving plan (ROADMAP item 1, arXiv
    2004.13336 applied to the serve path): Megatron tensor-parallel
    rules over the ``model`` axis for the functional transformer's
    params — the SAME ``tp_rules()`` spellings ``fsdp=True``-era
    training shards with — so one engine replica spans a whole mesh:
    attention projections and FFN matmuls shard over ``model``, the KV
    cache's kv-heads dimension shards with them, per-device param+KV
    bytes drop ~``model``× and GSPMD inserts the per-token collectives
    (one psum pair per block + the unembed gather) when the engine
    compiles its step.

    ``extra_rules`` prepend (first-match-wins, so they override);
    ``fsdp_axis`` additionally scatters still-unsharded params over
    that axis (gather-on-use — params only; the cache follows the
    attention-head rules, never fsdp).  See docs/serving_guide.md
    "Pod-sharded serving".
    """
    from distkeras_tpu.models.transformer import tp_rules

    return ServingPlan(rules=_override_rules(extra_rules, tp_rules()),
                       batch_spec=P(), fsdp_axis=fsdp_axis)


def dp_plan() -> ShardingPlan:
    """Pure data parallelism: replicate weights, split batch on ``data``."""
    return ShardingPlan(rules=(), batch_spec=P("data"))


def zero1_plan(bucket_mb: float | None = None) -> Zero1Plan:
    """Data parallelism with a cross-replica sharded weight update
    (ZeRO-1, arXiv 2004.13336): parameters replicated — forward and
    backward are byte-identical to :func:`dp_plan` — while optimizer
    state shards over ``data`` and each replica computes only its slice
    of the update (reduce-scatter(grads) -> shard update ->
    all-gather(update)).  Communication volume is unchanged (RS+AG ==
    the all-reduce it replaces); per-device optimizer memory and update
    FLOPs drop ~num_workers x.  Compare :func:`fsdp_plan` (ZeRO-3),
    which additionally scatters the *parameters* at the cost of an
    all-gather per use; see docs/zero1.md for when to prefer which.
    """
    return Zero1Plan(bucket_mb=bucket_mb)


def zero3_plan(bucket_mb: float | None = None) -> Zero3Plan:
    """Data parallelism with chunk-major gather-on-use parameter
    sharding (ZeRO-3): persistent params, gradients and optimizer
    state all live as ``[n, cols]`` shard views over ``data`` —
    per-device bytes for all three drop ~n× — and the step all-gathers
    parameters per fusion bucket just-in-time.  The explicit-plan
    spelling of ``zero=3`` on ADAG/DynSGD; compare :func:`fsdp_plan`
    (GSPMD dimension sharding, composes with TP) and
    :func:`zero1_plan` (update-only sharding, no gather-on-use).
    """
    return Zero3Plan(bucket_mb=bucket_mb)


def fsdp_plan(extra_rules: Sequence[tuple[str, P]] = (),
              axis: str = "data") -> ShardingPlan:
    """Fully-sharded data parallelism (ZeRO-3): weights and optimizer
    state scattered over the ``data`` axis, gathered on use.

    Same batch semantics as :func:`dp_plan`; per-device parameter and
    optimizer-state memory drops by ~the data-axis size, at the cost of
    an all-gather per use and a reduce-scatter per gradient (both ride
    the ICI).  The reference cannot express this at all — every worker
    and the parameter server hold full weight copies
    (distkeras/parameter_servers.py center variable).
    """
    return ShardingPlan(rules=extra_rules, batch_spec=P("data"),
                        fsdp_axis=axis)


def tp_plan(extra_rules: Sequence[tuple[str, P]] = ()) -> ShardingPlan:
    """Data + tensor parallelism for dense/conv/embedding stacks.

    Default rules follow the Megatron layout on the ``model`` axis:
    dense kernels column-sharded ([in, out] -> out over model); embeddings
    sharded over the vocab/feature dim; conv kernels over output channels.
    XLA turns the resulting partial products into psum/reduce-scatter on
    the ICI.
    """
    rules = _override_rules(extra_rules, [
        (r"(dense|mlp|fc)[^/]*/kernel$", P(None, "model")),
        (r"embedding[^/]*/embeddings$", P(None, "model")),
        (r"conv[^/]*/kernel$", P(None, None, None, "model")),
    ])
    return ShardingPlan(rules=rules, batch_spec=P("data"))
