"""Sharding plans: variable-path rules -> PartitionSpecs.

The reference has exactly one placement policy: the full weight vector
lives on the parameter server and full copies live on every worker
(distkeras/parameter_servers.py holds the "center variable").  Here
placement is a first-class, declarative plan: regex rules over Keras
variable paths map each parameter to a ``PartitionSpec`` on the mesh.
The default plan is pure data parallelism (weights replicated, batch
split over ``data``); a tensor-parallel plan shards the big matmul
operands over ``model`` and XLA inserts the all-gathers/reduce-scatters.
"""

from __future__ import annotations

import re
from typing import Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class ShardingPlan:
    """Ordered (regex, PartitionSpec) rules; first match wins.

    Unmatched variables are replicated.  Rules match against the Keras
    variable path (e.g. ``"dense_1/kernel"``).
    """

    def __init__(self, rules: Sequence[tuple[str, P]] = (),
                 batch_spec: P = P("data")):
        self.rules = [(re.compile(pat), spec) for pat, spec in rules]
        self.batch_spec = batch_spec

    def spec_for(self, path: str, ndim: int | None = None) -> P:
        for pat, spec in self.rules:
            if pat.search(path):
                return spec
        return P()

    # ------------------------------------------------------------- builders

    def param_shardings(self, mesh: Mesh, paths: Sequence[str]):
        """NamedShardings for a list-of-arrays pytree ordered like ``paths``."""
        return [NamedSharding(mesh, self.spec_for(p)) for p in paths]

    def state_shardings(self, mesh: Mesh, state, tv_paths: Sequence[str]):
        """Shardings pytree matching a :class:`TrainState`.

        ``tv`` (and its optimizer-state mirrors) get the plan's rules;
        ``ntv``/``step`` are replicated.  Optax states are pytrees whose
        array leaves mirror parameter shapes (mu/nu in adam etc.) or are
        scalars; we map any leaf whose shape matches a param positionally.
        """
        tv_sh = self.param_shardings(mesh, tv_paths)
        rep = NamedSharding(mesh, P())

        # Optax states embed subtrees mirroring the param pytree (our tv
        # is a flat list, so e.g. adam's mu/nu are lists in tv order).
        # Match each opt-state leaf to its param by the *index* of the
        # innermost list it sits in — positional, not shape-based, so
        # same-shaped params with different specs stay distinct.  A leaf
        # whose innermost-list index doesn't correspond to a matching
        # param shape (EmptyState internals, scalar counts) replicates.
        tv_list = list(state.tv)

        def opt_leaf_sharding(path, leaf):
            idx = None
            for key in reversed(path):
                if isinstance(key, jax.tree_util.SequenceKey):
                    idx = key.idx
                    break
            if (idx is not None and idx < len(tv_list)
                    and hasattr(leaf, "shape")
                    and tuple(leaf.shape) == tuple(tv_list[idx].shape)):
                return tv_sh[idx]
            return rep

        from distkeras_tpu.models.adapter import TrainState

        return TrainState(
            tv=tv_sh,
            ntv=jax.tree.map(lambda _: rep, state.ntv),
            opt_state=jax.tree_util.tree_map_with_path(
                opt_leaf_sharding, state.opt_state),
            step=rep,
        )

    def batch_sharding(self, mesh: Mesh) -> NamedSharding:
        return NamedSharding(mesh, self.batch_spec)

    def tree_shardings(self, mesh: Mesh, pytree):
        """NamedShardings for any pytree, rules keyed on jax key-paths.

        Paths are rendered like ``"layers/0/attn/wq"`` (keystr with the
        leading separator stripped), so the same regex rule language
        covers Keras variable paths and functional-model dicts.
        """
        def leaf(path, _):
            name = jax.tree_util.keystr(path, simple=True, separator="/")
            return NamedSharding(mesh, self.spec_for(name))

        return jax.tree_util.tree_map_with_path(leaf, pytree)


def dp_plan() -> ShardingPlan:
    """Pure data parallelism: replicate weights, split batch on ``data``."""
    return ShardingPlan(rules=(), batch_spec=P("data"))


def tp_plan(extra_rules: Sequence[tuple[str, P]] = ()) -> ShardingPlan:
    """Data + tensor parallelism for dense/conv/embedding stacks.

    Default rules follow the Megatron layout on the ``model`` axis:
    dense kernels column-sharded ([in, out] -> out over model); embeddings
    sharded over the vocab/feature dim; conv kernels over output channels.
    XLA turns the resulting partial products into psum/reduce-scatter on
    the ICI.
    """
    rules = list(extra_rules) + [
        (r"(dense|mlp|fc)[^/]*/kernel$", P(None, "model")),
        (r"embedding[^/]*/embeddings$", P(None, "model")),
        (r"conv[^/]*/kernel$", P(None, None, None, "model")),
    ]
    return ShardingPlan(rules=rules, batch_spec=P("data"))
