"""JAX version compatibility: the ONE import site for ``shard_map``.

The manual-sharding API graduated from ``jax.experimental.shard_map``
(kwargs ``check_rep`` / ``auto``) to top-level ``jax.shard_map``
(kwargs ``check_vma`` / ``axis_names``).  Every module in this package
imports the new-API surface from here; on an older jax the experimental
implementation is adapted (``check_vma -> check_rep``;  ``axis_names``
— the axes mapped manually — becomes its complement ``auto``, the axes
left automatic).  Without this shim a jax 0.4.x install cannot even
import the trainer family — the resilience gate runs nothing.
"""

from __future__ import annotations

try:
    from jax import shard_map  # jax >= 0.6
except ImportError:
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True,
                  axis_names=None):
        kw = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=check_vma)
        if axis_names is not None:
            kw["auto"] = frozenset(mesh.axis_names) - set(axis_names)
        return _shard_map(f, **kw)


def keystr(path, simple: bool = False, separator: str = "") -> str:
    """``jax.tree_util.keystr`` with the newer ``simple``/``separator``
    kwargs, emulated on a jax whose keystr takes only the path."""
    import jax

    try:
        return jax.tree_util.keystr(path, simple=simple,
                                    separator=separator)
    except TypeError:
        if not simple:
            return jax.tree_util.keystr(path)

        def entry(k):
            for attr in ("name", "key", "idx"):
                if hasattr(k, attr):
                    return str(getattr(k, attr))
            return str(k)

        return separator.join(entry(k) for k in path)


__all__ = ["shard_map", "keystr"]
