"""Continuous batching: a lane-based serving engine over the decode step.

Static-shape serving loop for interactive workloads: requests arrive at
different times, but the chip wants one fixed-shape program.  The
engine holds ``lanes`` decode rows in ONE KV cache and ONE jitted
per-row-position decode step (``generate._decode_chunk``'s non-uniform
path — the same machinery speculative decoding uses for accept
divergence); a new request is admitted into any free lane mid-flight
with a bucket-padded chunked prefill of just that lane, while the other
lanes keep decoding.  No compiled shape ever depends on arrival times.

Contract: every request's emitted tokens are EXACTLY what
``generate(params, prompt, cfg, max_new_tokens, ...)`` would emit for
it alone — the per-lane PRNG stream is position-keyed like generate's
(``fold_in(request_key, pos)``), lane-local positions start at 0 per
request, and stale cache slots from the lane's previous occupant are
masked until overwritten (the ``_decode_chunk`` staleness argument).
Pinned by tests/test_serving.py against solo ``generate`` runs,
including staggered admission and lane reuse.

The reference has no serving story at all (its ModelPredictor runs the
training forward over a static batch — reference:
distkeras/predictors.py); this module is TPU-first surplus on the
serving axis, alongside speculative decoding and the prefix cache.

Design notes:

- ``step()`` decodes ALL lanes every call (free lanes burn a row of
  compute — that is the price of one static program; at the measured
  decode roofline a wasted row costs ~1/lanes of a step).
- Admission prefills ``prompt[:-1]`` (bucket-padded) into the lane and
  sets the lane position to ``len(prompt) - 1``; the next ``step()``
  processes the final prompt token and samples the first new one —
  exactly generate()'s sequential convention, so no special logits
  plumbing exists for the first token.
- Compiles one admission program per prompt-length bucket and one
  n-step decode scan per DISTINCT ``step(n)`` window, each lazily and
  cached for the engine's lifetime — drive the loop with a small fixed
  set of window sizes (e.g. always ``step(8)``), not a per-call-varying
  ``n``, or each new value pays a fresh compile.
- Production admission control (docs/resilience.md) rides on top as
  pure host bookkeeping: per-request ``ttl``/``deadline`` with lane
  eviction and structured ``RequestResult``s, a bounded ``enqueue``
  queue with ``QueueFull`` backpressure, a drain-then-``shutdown()``
  lifecycle, and — on :class:`SpeculativeBatcher` — graceful
  degradation to the plain decode path when the draft model faults.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu import obs
from distkeras_tpu.resilience import chaos
from distkeras_tpu.resilience.admission import (EngineClosed, QueueFull,
                                                 RequestResult, _Pending)

from distkeras_tpu.models.generate import (
    _decode_chunk,
    _device_tree,
    _resolve_prompt_cache,
    init_cache,
    min_p_mask,
    rolling_eligible,
    top_k_mask,
    top_p_mask,
)
from distkeras_tpu.models.speculative import speculative_accept
from distkeras_tpu.models.transformer import TransformerConfig


@dataclasses.dataclass
class _Lane:
    request_id: int
    prompt_len: int
    max_new: int
    key: object          # per-request PRNG key (None for greedy)
    tokens: list         # host-side transcript, prompt included
    done: bool = False
    eos: object = None   # per-request eos token (engine default)
    deadline: float | None = None  # absolute clock() time; None = none
    managed: bool = False  # admitted via enqueue(): auto-collected
    born: float | None = None  # clock() at admission (obs latency)


def _make_lane_admit(model_params, model_cfg, off=0, prefix_lane=None):
    """ONE-lane admission program factory shared by both engines:
    prefill ``rows`` (bucket-padded) into a single lane's cache slice,
    seeded from ``prefix_lane`` (shared system prompt) or zeros — a
    fresh occupant must never see the previous request's K/V beyond
    its own positions.  Returns a jitted (cache, rows, lane) -> cache.
    """
    def admit(cache, rows, lane):
        lane_cache = jax.tree.map(
            lambda a: jax.lax.dynamic_slice_in_dim(a, lane, 1, axis=1),
            cache)
        if prefix_lane is not None:
            # prefill() returns a full-max_len cache with the prefix
            # slots filled and the rest zero — exactly the fresh-lane
            # seed we need.
            lane_cache = jax.tree.map(
                lambda z, pre: pre.astype(z.dtype),
                lane_cache, prefix_lane)
        else:
            lane_cache = jax.tree.map(jnp.zeros_like, lane_cache)
        _, lane_cache = _decode_chunk(
            model_params, lane_cache, rows,
            jnp.full((1,), off, jnp.int32), model_cfg,
            uniform_pos=True)
        return jax.tree.map(
            lambda a, u: jax.lax.dynamic_update_slice_in_dim(
                a, u, lane, axis=1), cache, lane_cache)
    return jax.jit(admit, donate_argnums=0)


class _LaneEngine:
    """Host-side lane machinery shared by the serving engines: the
    lane table, free/running/drain, and the per-step emission loop
    (append to the transcript, stop at budget or the lane's eos).

    Also the admission-control layer (resilience subsystem): request
    deadlines/TTLs, a bounded FIFO queue with :class:`QueueFull`
    backpressure, structured :class:`RequestResult` reporting, and the
    drain-then-shutdown lifecycle.  All of it is host bookkeeping —
    the compiled decode programs and their exact-parity contract are
    untouched (an evicted lane just stops being read; its rows keep
    burning compute until admission reseeds them, same as any done
    lane)."""

    def free_lanes(self):
        return [i for i, s in enumerate(self._lane_state) if s is None]

    def running(self):
        return [i for i, s in enumerate(self._lane_state)
                if s is not None and not s.done]

    def drain(self, lane):
        """Return the finished lane's [prompt + generation] tokens and
        free the lane; raises if the lane is still running."""
        st = self._lane_state[lane]
        if st is None:
            raise ValueError(f"lane {lane} is empty")
        if not st.done:
            raise ValueError(f"lane {lane} is still decoding")
        self._lane_state[lane] = None
        self._obs_request_done("ok", st.born)
        return np.asarray(st.tokens, np.int32)

    def _emit(self, lane_tokens):
        """Feed each live lane's new tokens (``lane_tokens(lane)``)
        through the transcript/budget/eos bookkeeping; returns the
        ``{lane: [emitted...]}`` step result.  The ONE site that
        counts emitted tokens (``serving.tokens``) — every step path
        funnels through here, so the throughput metric is
        structurally complete."""
        out = {}
        for lane, st in enumerate(self._lane_state):
            if st is None or st.done:
                continue
            emitted = []
            for tok in lane_tokens(lane):
                st.tokens.append(int(tok))
                emitted.append(int(tok))
                budget = len(st.tokens) - st.prompt_len >= st.max_new
                if budget or (st.eos is not None and tok == st.eos):
                    st.done = True
                    break
            out[lane] = emitted
        if obs.active() is not None:
            obs.count("serving.tokens",
                      sum(len(v) for v in out.values()))
        return out

    # ----------------------------------------------- admission control

    def _init_admission(self, max_queue: int, clock) -> None:
        if max_queue < 0:
            raise ValueError(f"max_queue must be >= 0, got {max_queue}")
        self.max_queue = max_queue
        self._clock = clock if clock is not None else time.monotonic
        self._pending = collections.deque()
        self._completed: dict[int, RequestResult] = {}
        self._closed = False
        # One lock makes the closed-check and the queue insert ATOMIC:
        # a begin_shutdown() racing an in-flight enqueue() must yield
        # exactly one of two outcomes — the request raised EngineClosed
        # (close won) or it is in the queue/lane and shutdown's drain
        # reaches it (insert won).  Without the lock, the enqueue could
        # pass the closed check, lose the race, and then raise
        # QueueFull off a queue that shutdown was already cancelling —
        # the caller would shed load from an engine that is not
        # overloaded, it is closing.  EngineClosed WINS: once
        # begin_shutdown returns, every later enqueue/submit raises it,
        # even when the queue is also full.  Reentrant because
        # enqueue -> pump -> _admit_pending nests.
        self._admission_lock = threading.RLock()
        self._admitting = False  # pump()-internal submit bypasses _closed
        # Elastic-tier bookkeeping (ContinuousBatcher(lane_tiers=...);
        # inert defaults for every other engine).
        self.lane_tiers = None
        self.tier_epoch = 0
        self.scale_up_after = 2
        self.scale_down_after = 8
        self._bp_strikes = 0
        self._idle_strikes = 0
        # The id under which the most recent bare submit() recorded (or
        # will record) its RequestResult — how drain()-style callers
        # that pass a ttl reach their structured timeout via poll/take
        # instead of the pop-everything results().
        self.last_request_id: int | None = None

    def _deadline_of(self, ttl, deadline):
        """Resolve submit/enqueue's ``ttl`` (seconds from now) /
        ``deadline`` (absolute ``clock()`` time) pair."""
        if ttl is not None and deadline is not None:
            raise ValueError("pass ttl (relative) OR deadline "
                             "(absolute), not both")
        if ttl is not None:
            return self._clock() + ttl
        return deadline

    def _check_open(self) -> None:
        if self._closed and not self._admitting:
            obs.count("serving.rejected", reason="closed")
            raise EngineClosed(
                "engine is shutting down (begin_shutdown was called); "
                "no new requests are admitted during drain")

    def _obs_request_done(self, status: str, born) -> None:
        """Terminal-request telemetry: status counter, deadline-miss
        counter, and the request latency histogram (engine clock, so
        chaos tests with an injected clock stay deterministic)."""
        obs.count("serving.requests", status=status)
        if status == "timeout":
            obs.count("serving.deadline_misses")
        if born is not None and obs.active() is not None:
            obs.observe("serving.request_s", self._clock() - born,
                        status=status)

    def _finish(self, rid: int, tokens, status: str, prompt_len: int,
                error: str | None = None, born=None):
        self._obs_request_done(status, born)
        self._completed[rid] = RequestResult(
            request_id=rid, tokens=np.asarray(tokens, np.int32),
            status=status, prompt_len=prompt_len, error=error)

    def _expired_on_arrival(self, dl, prompt, p: int) -> bool:
        """The ONE expired-on-arrival protocol for both engines'
        ``submit``: an already-dead request never occupies a lane; a
        caller-facing submit records the structured timeout under a
        fresh id (exposed as ``last_request_id``), while internal
        admission (enqueue/pump) declines silently — the caller records
        under the request's own id."""
        if dl is None or dl > self._clock():
            return False
        if not self._admitting:
            rid = self._next_id
            self._next_id += 1
            self._finish(rid, prompt, "timeout", p,
                         born=self._clock())
            self.last_request_id = rid
        return True

    def _admitted_id(self) -> int:
        """Allocate the admitted request's id; caller-facing submits
        expose it as ``last_request_id``."""
        rid = self._next_id
        self._next_id += 1
        if not self._admitting:
            self.last_request_id = rid
        return rid

    def _decline_full(self) -> None:
        """Engine-full decline: no request was registered, so a stale
        ``last_request_id`` must not masquerade as this request's."""
        if not self._admitting:
            obs.count("serving.rejected", reason="no_free_lane")
            self.last_request_id = None

    def enqueue(self, prompt, max_new_tokens: int, ttl=None, deadline=None,
                **submit_kw) -> int:
        """Admission-controlled submit: returns a request id
        immediately; the terminal :class:`RequestResult` arrives via
        :meth:`poll` / :meth:`take` / :meth:`results` once the request
        finishes, times out, or is cancelled by shutdown.

        No free lane: the request waits in the bounded FIFO queue
        (capacity ``max_queue``); past capacity, raises
        :class:`QueueFull` — the backpressure signal.  An already-
        expired deadline never occupies a lane or a queue slot: the
        structured timeout result is recorded up front.

        ``submit_kw`` forwards to this engine's ``submit`` (per-request
        key / sampling overrides / eos_token); engine-specific
        validation beyond the prompt/budget checks runs at admission
        time, which for a queued request is a later ``step()``.

        Thread safety: the closed check and the queue insert are
        atomic under one engine lock, and **EngineClosed wins** — an
        enqueue racing ``begin_shutdown`` either gets its request in
        (and shutdown's drain reaches it) or raises EngineClosed;
        QueueFull is only ever raised by an engine that is actually
        open and overloaded.  On elastic engines (``lane_tiers``),
        sustained overflow steps the lane tier up instead of raising
        (see the ContinuousBatcher docstring).
        """
        with self._admission_lock:
            self._check_open()
            prompt = np.asarray(prompt, np.int32).reshape(-1)
            if prompt.size < 1:
                raise ValueError("prompt must contain at least one token")
            if max_new_tokens < 1:
                raise ValueError(
                    f"max_new_tokens must be >= 1, got {max_new_tokens}")
            self._validate_budget(prompt.size, max_new_tokens)
            dl = self._deadline_of(ttl, deadline)
            rid = self._next_id
            self._next_id += 1
            if dl is not None and dl <= self._clock():
                # born=now: a ~0s latency observation, so the request_s
                # histogram count agrees with the requests counter (the
                # deadline-miss population must not vanish from it).
                self._finish(rid, prompt, "timeout", prompt.size,
                             born=self._clock())
                return rid
            pend = _Pending(rid, prompt, int(max_new_tokens), dl,
                            submit_kw, born=self._clock())
            # FIFO: queued requests get first claim on any free lane
            # (and expired heads are dropped) before this one may jump
            # in.
            self.pump()
            if self.free_lanes() and not self._pending:
                # Immediate admission: validation errors raise to the
                # caller here, synchronously.
                if self._admit_pending(pend):
                    self._bp_strikes = 0
                    return rid
                # A lane was free, so the only way submit declined is
                # the deadline expiring between our check and its
                # re-check.
                self._finish(rid, prompt, "timeout", prompt.size,
                             born=pend.born)
                return rid
            while len(self._pending) >= self.max_queue:
                if not self._try_scale_up():
                    obs.count("serving.rejected", reason="queue_full")
                    raise QueueFull(
                        f"all {self.lanes} lanes busy and the "
                        f"admission queue holds {len(self._pending)}/"
                        f"{self.max_queue} requests; shed load or "
                        "raise max_queue")
                # Fresh lanes: queued requests keep FIFO priority,
                # then this one takes a lane or the queue headroom.
                self.pump()
                if self.free_lanes() and not self._pending:
                    if self._admit_pending(pend):
                        return rid
                    self._finish(rid, prompt, "timeout", prompt.size,
                                 born=pend.born)
                    return rid
            self._bp_strikes = 0
            self._pending.append(pend)
            obs.gauge("serving.queue_depth", len(self._pending))
            return rid

    def _admit_pending(self, pend) -> bool:
        self._admitting = True
        try:
            lane = self.submit(pend.prompt, pend.max_new,
                               deadline=pend.deadline, **pend.submit_kw)
        finally:
            self._admitting = False
        if lane is None:
            return False
        st = self._lane_state[lane]
        # submit() allocated a fresh id; the request keeps the one its
        # caller holds (ids stay unique — the fresh one is just unused).
        st.request_id = pend.request_id
        st.managed = True
        if pend.born is not None:
            # Request latency counts from enqueue, queue wait included.
            st.born = pend.born
            if obs.active() is not None:
                obs.observe("serving.queue_wait_s",
                            self._clock() - pend.born)
        return True

    def pump(self) -> list[int]:
        """Admit queued requests into free lanes (FIFO); queued
        requests whose deadline expired are dropped with a structured
        timeout — they never occupy a lane.  Runs automatically at the
        start of every ``step()``; returns the admitted request ids."""
        with self._admission_lock:
            return self._pump_locked()

    def _pump_locked(self) -> list[int]:
        admitted = []
        while self._pending:
            pend = self._pending[0]
            if (pend.deadline is not None
                    and pend.deadline <= self._clock()):
                self._pending.popleft()
                self._finish(pend.request_id, pend.prompt, "timeout",
                             pend.prompt.size, born=pend.born)
                continue
            if not self.free_lanes():
                break
            self._pending.popleft()
            try:
                ok = self._admit_pending(pend)
            except Exception as e:  # noqa: BLE001 — deferred validation
                # Engine-specific validation that enqueue() could not
                # run up front (e.g. the key-iff-sampling rule) fails
                # at admission: the request must still reach a terminal
                # structured result, not crash the decode loop.
                self._finish(pend.request_id, pend.prompt, "error",
                             pend.prompt.size, error=str(e),
                             born=pend.born)
                continue
            if ok:
                admitted.append(pend.request_id)
            else:
                # Free lane + declined admission == the deadline
                # expired between pump's check and submit's re-check.
                self._finish(pend.request_id, pend.prompt, "timeout",
                             pend.prompt.size, born=pend.born)
        # Unconditionally: expired-head drops shrink the queue without
        # admitting anything, and the gauge must not report phantom
        # backlog (no-op when telemetry is disabled).
        obs.gauge("serving.queue_depth", len(self._pending))
        return admitted

    def _reap(self) -> None:
        """Post-step bookkeeping: collect finished managed lanes and
        evict deadline-expired running lanes (structured timeout with
        the partial transcript).  Evicted/collected lanes free
        immediately — the next pump()/submit() reuses them."""
        now = None
        for lane, st in enumerate(self._lane_state):
            if st is None:
                continue
            if st.done:
                if st.managed:
                    self._finish(st.request_id, st.tokens, "ok",
                                 st.prompt_len, born=st.born)
                    self._lane_state[lane] = None
                continue
            if st.deadline is not None:
                if now is None:
                    now = self._clock()
                if st.deadline <= now:
                    self._finish(st.request_id, st.tokens, "timeout",
                                 st.prompt_len, born=st.born)
                    self._lane_state[lane] = None

    # ------------------------------------------------- elastic tiers

    def _try_scale_up(self) -> bool:
        """One overflow strike; step the lane tier up once the
        backpressure is *sustained* (``scale_up_after`` consecutive
        overflowing enqueues).  Returns whether a resize happened —
        False means the caller raises QueueFull (non-elastic engine,
        top tier reached, or not sustained yet)."""
        if self.lane_tiers is None:
            return False
        i = self.lane_tiers.index(self.lanes)
        if i + 1 >= len(self.lane_tiers):
            return False
        self._bp_strikes += 1
        if self._bp_strikes < self.scale_up_after:
            return False
        self._resize_to(self.lane_tiers[i + 1])
        return True

    def _maybe_scale_down(self) -> None:
        """Hysteresis mirror of :meth:`_try_scale_up`: after
        ``scale_down_after`` consecutive steps with the queue empty and
        occupancy at or under the next tier down, shrink to it (free
        lanes burn a row of decode compute each step — the whole point
        of stepping back down).  Runs under the admission lock: the
        resize compacts ``_lane_state``, which a concurrent
        ``enqueue`` (the documented thread-safe surface) must never
        observe mid-move."""
        if self.lane_tiers is None:
            return
        with self._admission_lock:
            i = self.lane_tiers.index(self.lanes)
            if i == 0:
                return
            lower = self.lane_tiers[i - 1]
            busy = sum(1 for s in self._lane_state if s is not None)
            if busy <= lower and not self._pending:
                self._idle_strikes += 1
            else:
                self._idle_strikes = 0
                return
            if self._idle_strikes >= self.scale_down_after:
                self._resize_to(lower)

    def _resize_to(self, tier: int) -> None:
        """Move the engine to ``tier`` lanes through the pre-compiled
        resize program: occupied lanes compact into the low indices
        (their device rows gathered, their host records remapped), new
        lanes arrive free (stale rows — masked until admission
        overwrites them, the same contract as lane reuse).  Strictly
        host-plus-precompiled work: no compile, ever (pinned by
        ``scripts/check_compile_counts.py``'s elastic session)."""
        old = self.lanes
        keep = [i for i, s in enumerate(self._lane_state)
                if s is not None]
        assert len(keep) <= tier, "resize below occupancy"
        idx = keep + [0] * (tier - len(keep))
        # numpy, not jnp.asarray(list): the latter jit-compiles a
        # convert_element_type per target length — a recompile the
        # elastic session's zero-compile assertion would catch.
        self._resize_state(np.asarray(idx, np.int32))
        state: list = [None] * tier
        for j, i in enumerate(keep):
            state[j] = self._lane_state[i]
        self._lane_state = state
        self.lanes = tier
        self.tier_epoch += 1
        self._bp_strikes = self._idle_strikes = 0
        obs.gauge("serving.lanes_tier", tier)
        obs.count("serving.resizes",
                  direction="up" if tier > old else "down")
        obs.event("serving.resize", from_lanes=old, to_lanes=tier,
                  tier_epoch=self.tier_epoch)

    def _resize_state(self, idx) -> None:  # pragma: no cover
        raise NotImplementedError(
            "this engine does not support lane_tiers")

    # ------------------------------------------------------- results

    def poll(self, request_id: int):
        """The request's :class:`RequestResult`, or None if still
        queued/decoding."""
        return self._completed.get(request_id)

    def take(self, request_id: int):
        """Pop and return the request's result; raises KeyError if it
        has not finished."""
        return self._completed.pop(request_id)

    def results(self) -> dict:
        """Pop every completed result: ``{request_id: RequestResult}``."""
        out = self._completed
        self._completed = {}
        return out

    @property
    def queued(self) -> int:
        return len(self._pending)

    @property
    def closed(self) -> bool:
        return self._closed

    # ------------------------------------------------------ lifecycle

    def begin_shutdown(self) -> None:
        """Stop admission (submit/enqueue raise :class:`EngineClosed`);
        in-flight lanes and the queue keep decoding via ``step()``.
        Taken under the admission lock: any enqueue that already
        passed its closed check finishes its insert first (and will be
        drained), and every enqueue after this returns raises
        EngineClosed — never QueueFull (EngineClosed wins)."""
        with self._admission_lock:
            self._closed = True

    def shutdown(self, max_steps: int | None = None) -> dict:
        """Drain-then-shutdown: stop admission, run the decode loop
        until every queued and running request reaches a terminal state
        (finish, eos, or deadline), and return the collected results.

        ``max_steps`` bounds the drain; requests still unfinished when
        it trips are cancelled (structured ``"cancelled"`` results,
        partial transcripts for lanes already decoding).  Lanes that
        were admitted with bare ``submit()`` and already finished are
        left for their caller's ``drain()`` — only live work blocks
        shutdown.
        """
        self.begin_shutdown()
        steps = 0
        while self.running() or self._pending:
            if max_steps is not None and steps >= max_steps:
                break
            if not self.running() and not self.free_lanes():
                # Queue blocked behind finished-but-undrained manual
                # lanes: stepping cannot make progress.
                break
            self.step()
            steps += 1
        for pend in self._pending:
            self._finish(pend.request_id, pend.prompt, "cancelled",
                         pend.prompt.size, born=pend.born)
        self._pending.clear()
        obs.gauge("serving.queue_depth", 0)
        for lane, st in enumerate(self._lane_state):
            if st is not None and not st.done:
                self._finish(st.request_id, st.tokens, "cancelled",
                             st.prompt_len, born=st.born)
                self._lane_state[lane] = None
        return self.results()


class ContinuousBatcher(_LaneEngine):
    """Lane-based continuous batching over one jitted decode step.

    Args mirror ``generate``'s sampling surface: ``temperature``,
    ``top_k`` / ``top_p`` / ``min_p``, ``eos_token``, ``exact_top_k``
    — fixed per engine (they are compiled into the step).  Per-request
    PRNG keys arrive with ``submit``.

    ``per_request_sampling=True`` compiles the vectorized step instead
    (per-lane temperature/top_p/min_p carried as [lanes] device
    arrays): ``submit`` then takes per-request ``temperature`` /
    ``top_p`` / ``min_p`` / ``eos_token`` overrides — greedy and
    sampled requests mix in one batch, each still matching its solo
    ``generate`` run exactly.  The constructor values become the
    per-request DEFAULTS.  Off by default because the general program
    pays the nucleus sort and the sampling draw every step even for a
    greedy-only workload; ``top_k`` stays engine-level either way (a
    static shape baked into the program).

    ``lanes``: decode rows held by the engine; ``prompt_buckets``:
    admission pad widths (a prompt of length P uses the smallest
    bucket >= P - 1; one admission program compiles per bucket).

    Full-cache configs, or rope + ``attention_window`` configs — the
    latter run ROLLING lanes: every lane decodes past ``max_len`` on
    the ring-buffer cache with no total-length cap (prompts still must
    fit the ring), each request matching its solo rolling
    ``generate()`` run exactly.  No quantized-tree restriction — int8
    weights decode on the same chunk path — and every engine shape
    takes ``kv_int8=True`` (int8 KV cache; parity vs
    ``generate(kv_int8=True, use_prefill=False)``), rolling ring
    lanes included (round-5: the scale slabs ride the same ring-slot
    updates as the K/V).

    **Elastic lane tiers** (round-7, resilience subsystem):
    ``lane_tiers=(2, 4, 8)`` starts the engine at 2 lanes and moves it
    between the declared tiers under load — ``scale_up_after``
    consecutive queue overflows step the tier up (the overflowing
    enqueue is absorbed instead of raising :class:`QueueFull`);
    ``scale_down_after`` consecutive steps with the queue empty and
    occupancy fitting the next tier down step it back (free lanes burn
    a decode row per step — shrinking recovers that compute).  EVERY
    tier's programs — each ``step_windows`` decode window, each
    admission bucket, the inter-tier resize gathers — compile at
    construction, so no request ever pays a recompile
    (``scripts/check_compile_counts.py``'s ``serving_elastic`` budget
    pins it).  A resize compacts occupied lanes; lane ids are
    therefore unstable, so elastic engines admit through the id-keyed
    :meth:`enqueue` surface only (bare ``submit`` rejects).
    ``serving.lanes_tier`` / ``serving.resizes`` /
    ``serving.resize`` events expose the tier trajectory through obs,
    and ``tier_epoch`` counts resizes for drain/debug correlation.
    """

    def __init__(self, params, cfg: TransformerConfig, lanes: int = 8,
                 temperature: float = 0.0, top_k=None, top_p=None,
                 min_p=None, eos_token=None, exact_top_k: bool = False,
                 prompt_buckets=(8, 32, 128, 512), prompt_cache=None,
                 kv_int8: bool = False,
                 per_request_sampling: bool = False,
                 max_queue: int = 0, clock=None,
                 lane_tiers=None, scale_up_after: int = 2,
                 scale_down_after: int = 8, step_windows=(1,)):
        # Windowed configs: the engine runs ROLLING lanes — each lane
        # decodes past max_len on the ring-buffer cache (the unbounded
        # streaming-chat shape), which needs rope (positions beyond
        # max_len have no learned-table embedding) and a window that
        # fits the ring.  Non-rope windowed configs have no rolling
        # semantics, so they stay rejected rather than silently
        # becoming bounded.
        self._rolling = False
        if cfg.attention_window is not None:
            if not rolling_eligible(cfg):
                raise ValueError(
                    "windowed continuous batching runs rolling lanes, "
                    "which needs rope=True and attention_window <= "
                    "max_len (full-cache configs need no window)")
            if prompt_cache is not None:
                raise ValueError("prompt_cache requires a full-cache "
                                 "config (no attention_window)")
            # kv_int8 composes: the int8 ring slab is the same
            # slot-addressed slab update with scale slabs riding along.
            self._rolling = True
        # Elastic lane tiers (resilience subsystem): the engine starts
        # at the smallest tier and moves between PRE-COMPILED tiers
        # under load — every tier's programs compile at construction,
        # so no request ever pays a recompile (the admission-latency
        # analogue of the prompt-bucket contract).
        _tiers = None
        if lane_tiers is not None:
            _tiers = tuple(sorted({int(t) for t in lane_tiers}))
            if len(_tiers) < 2:
                raise ValueError(
                    f"lane_tiers needs >= 2 distinct tiers, got "
                    f"{lane_tiers} (a single fixed size is just lanes=)")
            if _tiers[0] < 1:
                raise ValueError(f"lane tiers must be >= 1, got {_tiers}")
            if scale_up_after < 1 or scale_down_after < 1:
                raise ValueError(
                    "scale_up_after/scale_down_after must be >= 1 "
                    f"(got {scale_up_after}, {scale_down_after})")
            _windows = tuple(sorted({int(n) for n in step_windows}))
            if not _windows or _windows[0] < 1:
                raise ValueError(
                    f"step_windows must be positive ints, got "
                    f"{step_windows}")
            if 1 not in _windows:
                raise ValueError(
                    "step_windows must include 1 — drain/shutdown "
                    "steps one token at a time")
            if max_queue < 1:
                raise ValueError(
                    "lane_tiers needs max_queue >= 1: the queue "
                    "overflow IS the scale-up signal")
            lanes = _tiers[0]
        if lanes < 1:
            raise ValueError(f"lanes must be >= 1, got {lanes}")
        if prompt_cache is not None and prompt_cache[1] >= cfg.max_len:
            raise ValueError(
                f"shared prefix length {prompt_cache[1]} must leave "
                f"room under max_len={cfg.max_len}")
        if (temperature <= 0
                and (top_k
                     or (top_p is not None and top_p < 1.0)
                     or (min_p is not None and min_p > 0.0))
                and not per_request_sampling):
            # With per-request sampling the constructor values are only
            # DEFAULTS; a filter default alongside a greedy default
            # temperature is legal (it applies to requests that
            # override the temperature).  The explicit no-op values
            # (top_p=1.0 / min_p=0.0) are legal everywhere — the same
            # round-6 contract as generate and submit().
            raise ValueError(
                "top_k/top_p/min_p need temperature > 0 (greedy always "
                "takes the argmax)")
        # Eager range checks: the scalar step validates these lazily at
        # first trace, but the per-request path bakes them into device
        # arrays where a bad value would sample silent garbage
        # (log of a negative min_p is NaN, which masks every token).
        if temperature < 0:
            raise ValueError(
                f"temperature must be >= 0, got {temperature}")
        if top_p is not None and not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        # min_p=0.0 is the explicit "no filter" value on EVERY engine
        # mode (round-6: same contract as generate and submit()).
        if min_p is not None and not 0.0 <= min_p <= 1.0:
            raise ValueError(f"min_p must be in [0, 1], got {min_p}")
        if eos_token is not None and not 0 <= eos_token < cfg.vocab_size:
            raise ValueError(
                f"eos_token {eos_token} outside vocab [0, "
                f"{cfg.vocab_size})")
        self.params = _device_tree(params)
        self.cfg = cfg
        self.lanes = lanes
        # Shared prefix (system prompt): every lane's request decodes
        # past a common prefilled prefix — same contract as
        # generate(prompt_cache=...); admission seeds the lane from the
        # prefix instead of zeros and all positions shift by its length.
        self._off = 0
        self._prefix_lane = None
        if prompt_cache is not None:
            # The ONE prompt_cache contract (generate's helper): batch
            # must be 1 here (b=1), the prefix quantization must match
            # the engine cache (build it with prefill(kv_int8=...)),
            # and the loosest budget (p=1, one new token) must fit;
            # per-request budgets are re-checked at submit.
            pc, self._off = _resolve_prompt_cache(
                prompt_cache, cfg, b=1, p=1, max_new_tokens=1,
                kv_int8=kv_int8, use_prefill=None)
            self._prefix_lane = jax.tree.map(jnp.asarray, pc)
        self.eos_token = eos_token
        self.temperature = temperature
        self.top_p = top_p
        self.min_p = min_p
        # Buckets clamp to the cache slots past the shared prefix and
        # always include the largest legal width, so any prompt that
        # fits the budget has an admission program.
        cap = cfg.max_len - self._off
        self._buckets = tuple(sorted(
            {min(int(w), cap) for w in prompt_buckets} | {cap}))
        self._lane_state: list[_Lane | None] = [None] * lanes
        self._next_id = 0
        # Admission control (resilience subsystem): ``max_queue`` bounds
        # the enqueue() backlog (0 = no queue: enqueue needs a free
        # lane); ``clock`` is the deadline clock (monotonic seconds;
        # injectable for deterministic chaos tests).
        self._init_admission(max_queue, clock)
        if _tiers is not None:
            self.lane_tiers = _tiers
            self.scale_up_after = scale_up_after
            self.scale_down_after = scale_down_after
            self._step_windows = _windows

        # Device state: one cache, per-lane next-position, per-lane
        # current token (the one the next step processes), per-lane key.
        # ``kv_int8``: the cache stores int8 K/V + f32 scales — halves
        # the dominant HBM term at batch where cache bytes rule
        # (+33% measured at b64, a LOSS at b8; see perf_serving.md) —
        # and every request still matches its solo
        # ``generate(kv_int8=True, use_prefill=False)`` run exactly:
        # both the admission chunk and the sequential path attend the
        # ALREADY-QUANTIZED cache position by position, unlike
        # prefill() which attends the prompt in full precision.
        # (Stored for introspection only, like ``lanes``; the runtime
        # switch is the ``k_scale`` leaf in ``self.cache``.)
        self.kv_int8 = kv_int8
        self.per_request_sampling = per_request_sampling
        self.cache = init_cache(cfg, lanes, kv_int8=kv_int8)
        self.pos = jnp.zeros((lanes,), jnp.int32)
        self.cur = jnp.zeros((lanes,), jnp.int32)
        sampling = temperature > 0 or per_request_sampling
        self.keys = (jnp.stack([jax.random.key(0)] * lanes)
                     if sampling else None)
        # Per-lane sampling params (per_request_sampling only):
        # constructor values are the defaults; submit() overrides the
        # admitted lane's slots.  top_p 1.0 / min_p 0.0 are exact
        # no-ops in the row-wise masks.
        if per_request_sampling:
            # Explicit dtype: weak-typed f32 and plain f32 are distinct
            # jit avals, and the elastic warmup's dummy states must hit
            # the exact programs the live state will use.
            self.temps = jnp.full((lanes,), float(temperature),
                                  jnp.float32)
            self.tps = jnp.full((lanes,), float(top_p or 1.0),
                                jnp.float32)
            self.mps = jnp.full((lanes,), float(min_p or 0.0),
                                jnp.float32)
        else:
            # Placeholder args keep one step signature across modes
            # (allocated once — step() is the latency-floor hot loop).
            self.temps = self.tps = self.mps = jnp.zeros((lanes,),
                                                         jnp.float32)
        if self.keys is None:
            self.keys = jnp.zeros((lanes,), jnp.int32)  # unused filler
            self._keyed = False
        else:
            self._keyed = True

        def pick(k, row, q):
            return jax.random.categorical(
                jax.random.fold_in(k, q), row)

        def one_step(cache, cur, pos, keys, temps, tps, mps):
            logits, cache = _decode_chunk(
                self.params, cache, cur[:, None], pos, cfg)
            logits = logits[:, 0]                      # [lanes, V]
            if per_request_sampling:
                # Vectorized per-lane params: greedy lanes (t <= 0)
                # take the argmax of the RAW logits; the sampled draw
                # is computed for every lane (one static program) and
                # selected per lane.
                safe_t = jnp.where(temps > 0, temps, 1.0)
                scaled = logits / safe_t[:, None]
                if top_k is not None:
                    scaled = top_k_mask(scaled, top_k, exact=exact_top_k)
                # tps == 1.0 rows bypass the nucleus mask entirely:
                # float cumsum can overshoot 1.0 and mask an
                # underflowed-tail token that solo generate (which
                # skips the mask when top_p is None) could sample —
                # the bypass keeps the exact-parity contract.
                # min_p's 0.0 no-op is exact as-is (log 0 = -inf).
                scaled = jnp.where(tps[:, None] >= 1.0, scaled,
                                   top_p_mask(scaled, tps[:, None]))
                scaled = min_p_mask(scaled, mps[:, None])
                nxt = jnp.where(temps > 0,
                                jax.vmap(pick)(keys, scaled, pos),
                                logits.argmax(axis=-1))
            elif temperature > 0:
                scaled = logits / temperature
                if top_k is not None:
                    scaled = top_k_mask(scaled, top_k, exact=exact_top_k)
                # top_p >= 1.0 bypasses the mask, like the per-request
                # path and generate's scalar path (round-6 parity fix):
                # the sorted cumsum can float-overshoot 1.0 and mask an
                # underflowed tail token "no filter" could sample.
                if top_p is not None and top_p < 1.0:
                    scaled = top_p_mask(scaled, top_p)
                # min_p 0.0 likewise means "no filter" (and the scalar
                # mask rejects a concrete 0.0 outright).
                if min_p is not None and min_p > 0.0:
                    scaled = min_p_mask(scaled, min_p)
                nxt = jax.vmap(pick)(keys, scaled, pos)
            else:
                nxt = logits.argmax(axis=-1)
            # Device-side invariant (full-cache engines): pos NEVER
            # exceeds max_len - 1.  Free/done lanes keep decoding (the
            # price of one static program) and would otherwise advance
            # unboundedly; the clamp pins them to re-processing the
            # last slot — their outputs are discarded and admission
            # reseeds the lane, so correctness no longer leans on
            # dynamic_update_slice's start-clamping.  Live lanes are
            # unaffected: submit() budgets guarantee they finish at
            # pos <= max_len - 1.  ROLLING (windowed) engines are the
            # exception by design: pos is unbounded (the ring slot is
            # pos % max_len), for idle lanes too — harmless, since
            # their writes land in slots admission reseeds and the
            # all-idle early-out in step() stops the clock entirely.
            nxt_pos = (pos + 1 if self._rolling
                       else jnp.minimum(pos + 1, cfg.max_len - 1))
            return cache, nxt.astype(jnp.int32), nxt_pos

        def make_step(n):
            def step_n(cache, cur, pos, keys, temps, tps, mps):
                def body(carry, _):
                    cache, cur, pos = carry
                    cache, cur, pos = one_step(cache, cur, pos, keys,
                                               temps, tps, mps)
                    return (cache, cur, pos), cur
                (cache, cur, pos), toks = jax.lax.scan(
                    body, (cache, cur, pos), None, length=n)
                return cache, cur, pos, toks.T        # [lanes, n]
            return jax.jit(step_n, donate_argnums=0)

        self._make_step, self._steps = make_step, {}

        # Admission: prefill `width` positions of ONE lane (lane-sliced
        # cache write; padded tail slots stay masked until the decode
        # loop overwrites them).  ONE jitted program — jax.jit
        # specializes per bucket-padded rows shape on its own.
        self._admit = _make_lane_admit(self.params, cfg, off=self._off,
                                       prefix_lane=self._prefix_lane)

        def reseed(cache, lane):
            """Copy the shared prefix into one lane (1-token prompts
            skip the admission chunk but still need the prefix K/V)."""
            return jax.tree.map(
                lambda a, pre: jax.lax.dynamic_update_slice_in_dim(
                    a, pre.astype(a.dtype), lane, axis=1),
                cache, self._prefix_lane)

        self._reseed = jax.jit(reseed, donate_argnums=0)

        if self.lane_tiers is not None:
            def resize(cache, cur, pos, keys, temps, tps, mps, idx):
                # Gather lanes idx[j] -> j across the WHOLE device
                # state; jit specializes one program per (from, to)
                # tier pair, all warmed below.
                cache = jax.tree.map(
                    lambda a: jnp.take(a, idx, axis=1), cache)
                g = lambda a: jnp.take(a, idx, axis=0)
                return (cache, g(cur), g(pos), g(keys), g(temps),
                        g(tps), g(mps))

            # No donation: the gathered output has a different lane
            # count, so nothing could be reused in place anyway (and
            # XLA would warn on every tier pair).
            self._resize = jax.jit(resize)
            self._compile_tiers()

    # ---------------------------------------------------- elastic tiers

    def _tier_state(self, tier: int):
        """A dummy device state at ``tier`` lanes with EXACTLY the live
        state's avals — the warmup vehicle that populates the jit
        caches every tier will hit.  Returned in step-argument order
        ``(cache, cur, pos, keys, temps, tps, mps)``."""
        cache = init_cache(self.cfg, tier, kv_int8=self.kv_int8)
        cur = jnp.zeros((tier,), jnp.int32)
        pos = jnp.zeros((tier,), jnp.int32)
        keys = (jnp.stack([jax.random.key(0)] * tier) if self._keyed
                else jnp.zeros((tier,), jnp.int32))
        if self.per_request_sampling:
            temps = jnp.full((tier,), float(self.temperature),
                             jnp.float32)
            tps = jnp.full((tier,), float(self.top_p or 1.0),
                           jnp.float32)
            mps = jnp.full((tier,), float(self.min_p or 0.0),
                           jnp.float32)
        else:
            temps = tps = mps = jnp.zeros((tier,), jnp.float32)
        return cache, cur, pos, keys, temps, tps, mps

    def _compile_tiers(self) -> None:
        """Compile EVERY tier's programs up front: each declared step
        window and each admission bucket at each tier, plus the resize
        gathers between adjacent tiers (both directions).  After this,
        the elastic engine's whole lifetime — admissions, decode
        windows, tier moves — runs on warm jit caches; the
        ``serving_elastic`` budget in scripts/compile_budget.json pins
        exactly that."""
        with obs.span("serving.compile_tiers", tiers=self.lane_tiers):
            for n in self._step_windows:
                if n not in self._steps:
                    self._steps[n] = self._make_step(n)
            for tier in self.lane_tiers:
                for n in self._step_windows:
                    # The step donates its cache: a fresh dummy per
                    # window.
                    self._steps[n](*self._tier_state(tier))
                for width in self._buckets:
                    cache = self._tier_state(tier)[0]
                    self._admit(cache, jnp.zeros((1, width), jnp.int32),
                                jnp.int32(0))
                if self._prefix_lane is not None:
                    self._reseed(self._tier_state(tier)[0],
                                 jnp.int32(0))
                # submit()'s host bookkeeping (lane-slot writes)
                # specializes per tier too — tiny scatters, but a
                # compile is a compile.
                ints = jnp.zeros((tier,), jnp.int32)
                ints.at[0].set(0)
                if self._keyed:
                    jnp.stack([jax.random.key(0)] * tier).at[0].set(
                        jax.random.key(0))
                if self.per_request_sampling:
                    jnp.zeros((tier,), jnp.float32).at[0].set(0.0)
            for a, b in zip(self.lane_tiers, self.lane_tiers[1:]):
                for frm, to in ((a, b), (b, a)):
                    cache, cur, pos, keys, temps, tps, mps = \
                        self._tier_state(frm)
                    self._resize(cache, cur, pos, keys, temps, tps, mps,
                                 jnp.zeros((to,), jnp.int32))

    def _resize_state(self, idx) -> None:
        (self.cache, self.cur, self.pos, self.keys, self.temps,
         self.tps, self.mps) = self._resize(
            self.cache, self.cur, self.pos, self.keys, self.temps,
            self.tps, self.mps, idx)

    # ------------------------------------------------------------ API

    def _validate_budget(self, p: int, max_new_tokens: int) -> None:
        if (not self._rolling
                and self._off + p + max_new_tokens > self.cfg.max_len):
            # Rolling engines have no total-length cap: lanes decode
            # past max_len on the ring (the admission bucket check
            # below still caps the PROMPT at the ring size — a longer
            # prompt's chunk would wrap mid-write).
            raise ValueError(
                f"prefix ({self._off}) + prompt ({p}) + "
                f"max_new_tokens ({max_new_tokens}) exceeds "
                f"max_len={self.cfg.max_len}")
        warm = p - 1
        if warm and next((w for w in self._buckets if w >= warm),
                         None) is None:
            raise ValueError(
                f"prompt length {p} exceeds the largest admission "
                f"bucket ({self._buckets[-1]} + 1); raise "
                "prompt_buckets")

    def submit(self, prompt, max_new_tokens: int, key=None,
               temperature=None, top_p=None, min_p=None, eos_token=None,
               ttl=None, deadline=None):
        """Admit one request; returns its lane id, or None if the
        engine is full.  ``prompt``: 1-D int tokens; ``key``: per-
        request PRNG key (required iff THIS request samples).

        ``temperature`` / ``top_p`` / ``min_p`` / ``eos_token``:
        per-request overrides of the engine defaults — engines built
        with ``per_request_sampling=True`` only (``eos_token`` is
        host-side bookkeeping and works on every engine).  Pass
        ``top_p=1.0`` / ``min_p=0.0`` (the explicit no-op values) for
        an unfiltered request on an engine whose default filters.
        ``top_p=1.0`` means "no nucleus filter" EVERYWHERE — here,
        the engine scalar path, and solo ``generate`` all bypass the
        mask at >= 1.0 (round-6 parity fix), so a request copying its
        solo call's ``top_p=1.0`` replays that run exactly.

        ``ttl`` (seconds from now) / ``deadline`` (absolute ``clock()``
        time): the request's deadline.  A request that is already
        expired never occupies a lane — its structured timeout result
        is recorded (see :meth:`results`) and None is returned; one
        that expires mid-decode is evicted at the next ``step()`` the
        same way.  Deadline-carrying requests report through
        ``poll``/``take``/``results``, not ``drain``; this request's id
        is exposed as ``self.last_request_id`` (the queue-level
        :meth:`enqueue` API wraps all of this and returns the request
        id directly).

        Elastic engines (``lane_tiers=``) reject bare ``submit``: lane
        indices are not stable across tier resizes, so requests must go
        through the id-keyed :meth:`enqueue` surface.

        The whole admission runs under the engine lock, so a submit
        racing ``begin_shutdown`` either lands its lane before the
        drain looks (and is drained) or raises EngineClosed — the same
        contract :meth:`enqueue` documents.
        """
        with self._admission_lock:
            return self._submit_locked(prompt, max_new_tokens, key,
                                       temperature, top_p, min_p,
                                       eos_token, ttl, deadline)

    def _submit_locked(self, prompt, max_new_tokens, key, temperature,
                       top_p, min_p, eos_token, ttl, deadline):
        if self.lane_tiers is not None and not self._admitting:
            raise ValueError(
                "elastic engines (lane_tiers=...) admit through "
                "enqueue(): a tier resize compacts lanes, so the lane "
                "id submit() would return can dangle")
        self._check_open()
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        p = prompt.size
        if p < 1:
            raise ValueError("prompt must contain at least one token")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if ((temperature is not None or top_p is not None
             or min_p is not None) and not self.per_request_sampling):
            raise ValueError(
                "per-request temperature/top_p/min_p need "
                "ContinuousBatcher(per_request_sampling=True) — the "
                "default engine compiles the constructor's sampling "
                "params into the step")
        if top_p is not None and not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if min_p is not None and not 0.0 <= min_p <= 1.0:
            # 0.0 is the explicit "no min-p filter" override.
            raise ValueError(f"min_p must be in [0, 1], got {min_p}")
        if temperature is not None and temperature < 0:
            raise ValueError(
                f"temperature must be >= 0, got {temperature}")
        if eos_token is not None and not (
                0 <= eos_token < self.cfg.vocab_size):
            raise ValueError(
                f"eos_token {eos_token} outside vocab [0, "
                f"{self.cfg.vocab_size})")
        eff_t = self.temperature if temperature is None else temperature
        if eff_t <= 0 and ((top_p is not None and top_p < 1.0)
                           or (min_p is not None and min_p > 0.0)):
            # The explicit no-op values (top_p=1.0 / min_p=0.0) stay
            # legal on greedy requests — they turn a default filter OFF.
            raise ValueError(
                "per-request top_p/min_p need a sampling temperature "
                f"(effective temperature is {eff_t})")
        self._validate_budget(p, max_new_tokens)
        if (key is None) == (eff_t > 0):
            raise ValueError(
                "pass a per-request key iff this request samples "
                f"(effective temperature={eff_t})")
        dl = self._deadline_of(ttl, deadline)
        if self._expired_on_arrival(dl, prompt, p):
            # The acceptance contract: an already-dead request never
            # occupies a lane; its timeout is a structured result.
            return None
        free = self.free_lanes()
        if not free:
            self._decline_full()
            return None
        lane = free[0]
        chaos.probe("serving.admit")

        warm = p - 1
        if warm:
            width = next(w for w in self._buckets if w >= warm)
            rows = np.zeros((1, width), np.int32)
            rows[0, :warm] = prompt[:-1]
            with obs.span("serving.admit", bucket=width):
                self.cache = self._admit(
                    self.cache, jnp.asarray(rows), jnp.int32(lane))
        elif self._prefix_lane is not None:
            # 1-token prompt: no admission chunk runs, but the lane
            # still needs the shared prefix's K/V (code-review
            # regression: skipping this read zeros where the prefix
            # belongs).
            self.cache = self._reseed(self.cache, jnp.int32(lane))
        # else: 1-token prompt, no prefix — stale slots stay masked
        # until the decode loop overwrites them.
        self.pos = self.pos.at[lane].set(self._off + warm)
        self.cur = self.cur.at[lane].set(int(prompt[-1]))
        if self._keyed and key is not None:
            self.keys = self.keys.at[lane].set(key)
        if self.per_request_sampling:
            self.temps = self.temps.at[lane].set(float(eff_t))
            self.tps = self.tps.at[lane].set(float(
                (self.top_p or 1.0) if top_p is None else top_p))
            self.mps = self.mps.at[lane].set(float(
                (self.min_p or 0.0) if min_p is None else min_p))

        self._lane_state[lane] = _Lane(
            request_id=self._admitted_id(), prompt_len=p,
            max_new=max_new_tokens, key=key, tokens=list(prompt),
            eos=self.eos_token if eos_token is None else eos_token,
            deadline=dl, born=self._clock())
        return lane

    def traced_for_analysis(self):
        """Trace targets for the IR lint (analysis/ir_lint.py): the
        jitted single-token decode step over the engine's live lane
        state.  Nothing executes — the lint traces and lowers only."""
        from distkeras_tpu.analysis.ir_lint import TraceSpec

        if 1 not in self._steps:
            self._steps[1] = self._make_step(1)
        mode = ("per_request" if self.per_request_sampling
                else "sampled" if self.temperature > 0 else "greedy")
        return [TraceSpec(
            name=f"continuousbatcher_{mode}/decode_step",
            fn=self._steps[1],
            args=(self.cache, self.cur, self.pos, self.keys,
                  self.temps, self.tps, self.mps),
            donate_argnums=(0,))]

    def step(self, n: int = 1):
        """Advance every lane ``n`` tokens in ONE device round-trip;
        returns ``{lane: [tokens...]}`` for lanes that emitted.

        ``n > 1`` amortizes the per-dispatch host/relay latency (the
        measured floor is ~1.6 ms — comparable to a whole decode step
        at batch 8) at the cost of admission granularity: new requests
        wait for the window to finish, and a lane that hits its
        eos/budget mid-window keeps decoding privately — the surplus
        tokens are discarded here, identical to truncating generate()'s
        sticky-fill output.  Emitted tokens are EXACTLY step(1)'s.

        Runs under the engine lock end to end: a concurrent
        ``enqueue`` can trigger a tier resize (scale-up), and the
        device state this step captures must not be swapped and
        compacted under it mid-round-trip.
        """
        if n < 1:
            raise ValueError(f"n must be >= 1, got {n}")
        if self.lane_tiers is not None and n not in self._step_windows:
            raise ValueError(
                f"elastic engines pre-compile their decode windows; "
                f"step({n}) is not in step_windows={self._step_windows}"
                " — declare it at construction (a lazy compile here "
                "would break the no-recompile contract across tiers)")
        with self._admission_lock:
            self.pump()
            # Tier hysteresis BEFORE the idle early-out: an idle
            # elastic engine must still step its lane count back down.
            self._maybe_scale_down()
            # Idle engine (every lane empty or finished-but-
            # undrained): nothing can emit, so skip the device
            # round-trip entirely instead of burning a full decode
            # window.
            if all(s is None or s.done for s in self._lane_state):
                return {}
            chaos.probe("serving.step")
            if obs.active() is not None:  # running() is O(lanes)
                obs.gauge("serving.lanes_busy", len(self.running()))
            if n not in self._steps:
                self._steps[n] = self._make_step(n)
            with obs.span("serving.step", n=n):
                self.cache, self.cur, self.pos, toks = self._steps[n](
                    self.cache, self.cur, self.pos, self.keys,
                    self.temps, self.tps, self.mps)
                toks = np.asarray(toks)
            out = self._emit(lambda lane: toks[lane].tolist())
            # Deadline granularity is one step window: tokens emitted
            # in the window that straddles the deadline are kept in
            # the partial result.
            self._reap()
            return out


class SpeculativeBatcher(_LaneEngine):
    """Draft-assisted continuous batching: every lane advances up to
    ``n_draft + 1`` positions per device round-trip.

    The lane/admission machinery is :class:`ContinuousBatcher`'s; the
    step is one iteration of :func:`speculative_generate`'s body
    vectorized over lanes at divergent positions — ``n_draft`` draft
    proposals (the draft's first chunk is T=2, closing the
    full-acceptance cache gap exactly like the solo loop), ONE target
    verify chunk, per-lane acceptance, and a per-lane advance of
    ``accepted + 1`` tokens.  Rejected-tail cache writes land
    beyond each lane's frontier and are masked until overwritten
    (the _decode_chunk staleness argument), so lanes never interact.

    Contract: every request's emitted tokens are EXACTLY its solo
    ``speculative_generate`` run's (batch 1, same key).  Greedy
    (``temperature=0``) that is ``generate``'s greedy rollout;
    sampled (engine-level ``temperature > 0``, per-request keys) it
    is the Leviathan/Chen speculative-sampling rollout — each lane
    carries its own iteration counter so its accept/corrective draws
    replay the solo run's ``fold_in(key, iteration)`` stream exactly,
    whenever the lane was admitted.  Scope: no shared prefix, no
    top-k/p filters (the solo fn has none either); unsupported
    combinations reject loudly.

    Budget (full-cache): a request needs ``prompt + max_new_tokens +
    n_draft <= max_len`` on BOTH models (the verify chunk writes
    ``n_draft + 1`` slots past the frontier; same slack as the solo
    fn).  Finished lanes keep decoding with their frontier clamped at
    the last budget-safe position — outputs discarded, admission
    reseeds.

    ROLLING lanes (round-7): when BOTH configs are windowed
    (rope + ``attention_window``, with ``window + n_draft + 1 <=
    max_len`` each — solo speculative's ring bound), lanes decode past
    ``max_len`` on the ring caches with no total-length cap (prompts
    still must fit the ring), matching solo windowed
    ``speculative_generate`` per request; and the draft-fault FALLBACK
    is ring-compatible — it inherits the lanes' unbounded positions
    and ring slabs mid-wrap, so greedy parity with solo rolling
    ``generate`` holds past ``max_len`` through a degradation.
    """

    def __init__(self, params, draft_params, cfg: TransformerConfig,
                 draft_cfg: TransformerConfig, lanes: int = 8,
                 n_draft: int = 4, temperature: float = 0.0,
                 eos_token=None, prompt_buckets=(8, 32, 128, 512),
                 max_queue: int = 0, clock=None):
        # Windowed configs run ROLLING speculative lanes (round-7): the
        # verify chunk writes through _decode_chunk's modular ring
        # scatter under the same bound as solo speculative_generate —
        # window + n_draft + 1 <= max_len keeps every rejected tail's
        # slots outside every live query's band — and lanes decode past
        # max_len with no total-length cap, exactly like rolling
        # ContinuousBatcher lanes.  Crucially the DEGRADED path stays
        # ring-compatible too: the target-only fallback advances the
        # same unbounded per-lane positions over the same ring slabs,
        # so a draft fault mid-wrap preserves greedy solo parity past
        # max_len (the PR-1 follow-up).  Mixed full/windowed model
        # pairs stay rejected: their caches disagree on what a
        # position IS past the smaller ring.
        self._rolling = False
        if (cfg.attention_window is None) != (draft_cfg.attention_window
                                              is None):
            raise ValueError(
                "speculative serving needs the target and draft caches "
                "to agree: both full-cache or both windowed (got "
                f"target window={cfg.attention_window}, draft "
                f"window={draft_cfg.attention_window})")
        if cfg.attention_window is not None:
            for name, c in (("cfg", cfg), ("draft_cfg", draft_cfg)):
                if not rolling_eligible(c):
                    raise ValueError(
                        f"windowed speculative serving runs rolling "
                        f"lanes, which needs {name}.rope=True and "
                        f"attention_window <= max_len")
                if c.attention_window + n_draft + 1 > c.max_len:
                    raise ValueError(
                        f"rolling speculative lanes need "
                        f"{name}.attention_window "
                        f"({c.attention_window}) + n_draft + 1 "
                        f"({n_draft + 1}) <= max_len ({c.max_len}): "
                        "the verify chunk's rejected tail must alias "
                        "outside every live query's band")
            self._rolling = True
        if draft_cfg.vocab_size != cfg.vocab_size:
            raise ValueError(
                f"draft vocab_size {draft_cfg.vocab_size} != target "
                f"{cfg.vocab_size} — the models must share a tokenizer")
        if n_draft < 1:
            raise ValueError(f"n_draft must be >= 1, got {n_draft}")
        # Eager impossibility check: _cap = min(max_len) - n_draft - 1
        # is the largest prompt+generation budget any request can use;
        # _cap <= 0 means NO request can ever be admitted, so fail at
        # construction naming the real culprits instead of letting
        # every submit() blame the prompt.
        if min(cfg.max_len, draft_cfg.max_len) <= n_draft + 1:
            raise ValueError(
                f"n_draft={n_draft} leaves no decode budget: the verify "
                f"chunk needs n_draft + 1 cache slots of slack, but "
                f"min(max_len)={min(cfg.max_len, draft_cfg.max_len)} "
                f"(target {cfg.max_len}, draft {draft_cfg.max_len}) <= "
                f"n_draft + 1 = {n_draft + 1}; lower n_draft or raise "
                "max_len")
        if lanes < 1:
            raise ValueError(f"lanes must be >= 1, got {lanes}")
        if temperature < 0:
            raise ValueError(
                f"temperature must be >= 0, got {temperature}")
        if eos_token is not None and not 0 <= eos_token < cfg.vocab_size:
            raise ValueError(
                f"eos_token {eos_token} outside vocab [0, "
                f"{cfg.vocab_size})")
        self.params = _device_tree(params)
        self.draft_params = _device_tree(draft_params)
        self.cfg, self.draft_cfg = cfg, draft_cfg
        self.lanes, self.n_draft = lanes, n_draft
        self.temperature = temperature
        self.eos_token = eos_token
        # The verify chunk writes k+1 slots past the frontier on BOTH
        # caches; bucket admission caps prompts the same way.  Rolling
        # engines have no frontier cap (positions are unbounded on the
        # ring) — only the prompt must fit it: the admission warm
        # chunk is uniform-pos and must not wrap, so p - 1 <= ring - 1.
        if self._rolling:
            self._cap = None
            bucket_cap = min(cfg.max_len, draft_cfg.max_len) - 1
        else:
            self._cap = min(cfg.max_len, draft_cfg.max_len) - n_draft - 1
            bucket_cap = self._cap
        self._buckets = tuple(sorted(
            {min(int(w), bucket_cap) for w in prompt_buckets}
            | {bucket_cap}))
        self._lane_state: list[_Lane | None] = [None] * lanes
        self._next_id = 0
        self._init_admission(max_queue, clock)
        # Graceful degradation: when the draft half of the step faults
        # (chaos-injected, or a real dispatch failure caught with the
        # engine state intact), the engine permanently switches to a
        # plain target-only decode step — requests still complete,
        # just without the speculative speedup.  Greedy engines keep
        # exact solo-generate parity through the switch (greedy
        # speculative == greedy generate by construction); sampled
        # engines keep drawing valid samples but on a different PRNG
        # stream than the solo speculative rollout.
        self._degraded = False
        self.degraded_error = None
        self._fallback = None

        self.tcache = init_cache(cfg, lanes)
        self.dcache = init_cache(draft_cfg, lanes)
        self.pos = jnp.zeros((lanes,), jnp.int32)   # last FINAL position
        self.cur = jnp.zeros((lanes,), jnp.int32)   # token at pos
        self.prev = jnp.zeros((lanes,), jnp.int32)  # token at pos - 1
        # Sampled mode: per-lane request keys + per-lane ITERATION
        # counters — a lane's draws are keyed fold_in(key, iter) like
        # the solo loop's, so wherever the lane was admitted it
        # replays its solo b=1 run's PRNG stream exactly (RNG bits are
        # shape-row invariant: (V,) and (1, V) draws agree).
        self.keys = jnp.stack([jax.random.key(0)] * lanes)
        self.iters = jnp.zeros((lanes,), jnp.int32)

        k = n_draft
        idx = jnp.arange(k + 1)
        rolling = self._rolling
        cap = None if rolling else jnp.int32(self._cap)
        sampled = temperature > 0

        def step_fn(tcache, dcache, prev, cur, pos, keys, iters):
            # ---- draft: first chunk T=2 rewrites [pos-1, pos] (the
            # full-acceptance gap closure, exactly the solo body's).
            pos0 = jnp.maximum(pos - 1, 0)
            first = jnp.where(
                (pos == 0)[:, None],
                jnp.stack([cur, jnp.zeros_like(cur)], axis=1),
                jnp.stack([prev, cur], axis=1))
            lg2, dcache = _decode_chunk(self.draft_params, dcache,
                                        first, pos0, draft_cfg)
            lg = jnp.take_along_axis(
                lg2, (pos - pos0)[:, None, None], axis=1)[:, 0]
            kit = jax.vmap(jax.random.fold_in)(keys, iters)
            d_toks, q_logps = [], []
            for j in range(k):
                if sampled:
                    logp = jax.nn.log_softmax(lg / temperature, axis=-1)
                    nxt = jax.vmap(
                        lambda kk, row, _j=j: jax.random.categorical(
                            jax.random.fold_in(kk, _j), row))(kit, logp)
                    q_logps.append(logp)
                else:
                    nxt = lg.argmax(axis=-1)
                nxt = nxt.astype(jnp.int32)
                d_toks.append(nxt)
                if j < k - 1:
                    lgj, dcache = _decode_chunk(
                        self.draft_params, dcache, nxt[:, None],
                        pos + 1 + j, draft_cfg)
                    lg = lgj[:, 0]
            d = jnp.stack(d_toks, axis=1)               # [lanes, k]

            # ---- one target verify chunk over [cur, d_1..d_k]
            chunk = jnp.concatenate([cur[:, None], d], axis=1)
            tlog, tcache = _decode_chunk(self.params, tcache, chunk,
                                         pos, cfg)
            if sampled:
                # The Leviathan/Chen rule via the ONE shared
                # definition (speculative.speculative_accept); only
                # the draw keys differ from the solo loop — per-lane
                # iteration-keyed so each lane replays its solo run.
                p_logp = jax.nn.log_softmax(tlog / temperature, -1)
                q_logp = jnp.stack(q_logps, axis=1)
                u = jax.vmap(lambda kk: jax.random.uniform(
                    jax.random.fold_in(kk, k + 1), (k,)))(kit)
                n, corr_logits = speculative_accept(p_logp, q_logp,
                                                    d, u)
                corrective = jax.vmap(
                    lambda kk, row: jax.random.categorical(
                        jax.random.fold_in(kk, k + 2),
                        row))(kit, corr_logits).astype(jnp.int32)
            else:
                t_pred = tlog.argmax(axis=-1).astype(jnp.int32)
                match = d == t_pred[:, :k]
                n = jnp.cumprod(match, axis=1).sum(axis=1)   # [lanes]
                corrective = jnp.take_along_axis(t_pred, n[:, None],
                                                 axis=1)[:, 0]
            d_ext = jnp.concatenate([d, d[:, -1:]], axis=1)
            win = jnp.where(idx[None, :] < n[:, None], d_ext,
                            corrective[:, None]).astype(jnp.int32)

            # ---- advance: accepted + corrective.  Full-cache: the
            # frontier clamps at the budget-safe cap (live lanes never
            # reach it — submit guarantees total - 1 <= cap; clamped
            # lanes spin and the host discards their output).
            # Rolling: positions are unbounded — the ring absorbs any
            # advance (idle/done lanes keep rolling too; their writes
            # land in slots admission reseeds, like the rolling
            # ContinuousBatcher).
            if rolling:
                adv = (n + 1).astype(jnp.int32)
            else:
                adv = jnp.where(pos >= cap, 0,
                                jnp.minimum(n + 1, cap - pos)
                                ).astype(jnp.int32)
            new_pos = pos + adv
            last = jnp.take_along_axis(
                win, jnp.maximum(adv - 1, 0)[:, None], axis=1)[:, 0]
            new_cur = jnp.where(adv > 0, last, cur)
            second_last = jnp.take_along_axis(
                win, jnp.maximum(adv - 2, 0)[:, None], axis=1)[:, 0]
            new_prev = jnp.where(adv >= 2, second_last,
                                 jnp.where(adv == 1, cur, prev))
            return (tcache, dcache, new_prev, new_cur, new_pos,
                    iters + 1, win, adv)

        self._step = jax.jit(step_fn, donate_argnums=(0, 1))

        # Admission: one jitted program per MODEL (jit specializes per
        # bucket-padded rows shape); no shared-prefix support in v1.
        self._admit_t = _make_lane_admit(self.params, cfg)
        self._admit_d = _make_lane_admit(self.draft_params, draft_cfg)

    # -------------------------------------------------------------- API

    def traced_for_analysis(self):
        """Trace targets for the IR lint: the jitted speculative
        draft+verify step over the engine's live lane state."""
        from distkeras_tpu.analysis.ir_lint import TraceSpec

        mode = "sampled" if self.temperature > 0 else "greedy"
        return [TraceSpec(
            name=f"speculativebatcher_{mode}/step",
            fn=self._step,
            args=(self.tcache, self.dcache, self.prev, self.cur,
                  self.pos, self.keys, self.iters),
            donate_argnums=(0, 1))]

    def _validate_budget(self, p: int, max_new_tokens: int) -> None:
        if self._rolling:
            # No total-length cap: lanes roll past max_len on the
            # ring.  Only the PROMPT is bounded — its warm chunk is
            # uniform-pos and must not wrap.
            if p - 1 > self._buckets[-1]:
                raise ValueError(
                    f"prompt length {p} exceeds the largest admission "
                    f"bucket ({self._buckets[-1]} + 1); rolling "
                    "speculative prompts must fit the ring")
            return
        if p + max_new_tokens - 1 > self._cap:
            raise ValueError(
                f"prompt ({p}) + max_new_tokens ({max_new_tokens}) + "
                f"n_draft ({self.n_draft}) exceeds "
                f"max_len={min(self.cfg.max_len, self.draft_cfg.max_len)}"
                " (the verify chunk needs n_draft + 1 slots of slack)")

    def submit(self, prompt, max_new_tokens: int, key=None,
               eos_token=None, ttl=None, deadline=None):
        """Admit one request; returns its lane id, or None if full.
        ``key``: per-request PRNG key (required iff the engine
        samples, i.e. ``temperature > 0``).  ``ttl``/``deadline``:
        request deadline, same contract as
        :meth:`ContinuousBatcher.submit` — including holding the
        engine lock for the whole admission, so a submit racing
        ``begin_shutdown`` is either drained or raises EngineClosed."""
        with self._admission_lock:
            return self._submit_locked(prompt, max_new_tokens, key,
                                       eos_token, ttl, deadline)

    def _submit_locked(self, prompt, max_new_tokens, key, eos_token,
                       ttl, deadline):
        self._check_open()
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        p = prompt.size
        if p < 1:
            raise ValueError("prompt must contain at least one token")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if (key is None) == (self.temperature > 0):
            raise ValueError(
                "pass a per-request key iff the engine samples "
                f"(temperature={self.temperature})")
        self._validate_budget(p, max_new_tokens)
        if eos_token is not None and not (
                0 <= eos_token < self.cfg.vocab_size):
            raise ValueError(
                f"eos_token {eos_token} outside vocab [0, "
                f"{self.cfg.vocab_size})")
        dl = self._deadline_of(ttl, deadline)
        if self._expired_on_arrival(dl, prompt, p):
            return None
        free = self.free_lanes()
        if not free:
            self._decline_full()
            return None
        lane = free[0]
        chaos.probe("serving.admit")
        warm = p - 1
        if warm:
            # The budget check above bounds warm < cap, and _buckets
            # always contains cap, so a bucket always exists.
            width = next(w for w in self._buckets if w >= warm)
            rows = np.zeros((1, width), np.int32)
            rows[0, :warm] = prompt[:-1]
            rows_j = jnp.asarray(rows)
            with obs.span("serving.admit", bucket=width):
                self.tcache = self._admit_t(self.tcache, rows_j,
                                            jnp.int32(lane))
                self.dcache = self._admit_d(self.dcache, rows_j,
                                            jnp.int32(lane))
        # else: stale slots stay masked until overwritten.
        self.pos = self.pos.at[lane].set(p - 1)
        self.cur = self.cur.at[lane].set(int(prompt[-1]))
        self.prev = self.prev.at[lane].set(
            int(prompt[-2]) if p > 1 else 0)
        if key is not None:
            self.keys = self.keys.at[lane].set(key)
        self.iters = self.iters.at[lane].set(0)
        self._lane_state[lane] = _Lane(
            request_id=self._admitted_id(), prompt_len=p,
            max_new=max_new_tokens, key=key, tokens=list(prompt),
            eos=self.eos_token if eos_token is None else eos_token,
            deadline=dl, born=self._clock())
        return lane

    # ------------------------------------------------- degraded mode

    @property
    def degraded(self) -> bool:
        """True once the engine fell back to the plain decode path."""
        return self._degraded

    def degrade(self, error=None) -> None:
        """Permanently switch to the target-only fallback decode step
        (see the constructor's degradation note).  Called automatically
        when the draft half of a step faults; callable directly by an
        operator who knows the draft model is bad."""
        if not self._degraded:
            obs.count("serving.degraded")
            obs.event("serving.degraded",
                      error=None if error is None else repr(error))
        self._degraded = True
        if error is not None and self.degraded_error is None:
            self.degraded_error = error

    def _note_draft_fault(self, e: BaseException) -> None:
        intact = not any(
            getattr(leaf, "is_deleted", lambda: False)()
            for leaf in jax.tree.leaves(
                (self.tcache, self.cur, self.pos, self.keys)))
        if not intact:
            raise RuntimeError(
                "draft fault surfaced after the speculative step "
                "consumed its donated state; the fallback path has "
                "nothing valid to decode from") from e
        self.degrade(e)

    def _make_fallback(self):
        """Plain target-only decode step over the SAME engine state
        (tcache/cur/pos): one token per lane per call, frontier clamped
        at the budget-safe cap exactly like the speculative step —
        except on ROLLING engines, where the fallback preserves the
        ring-slot arithmetic instead: positions stay unbounded and each
        row keeps writing slot ``pos % max_len``, so a draft fault
        mid-wrap hands the plain path a cache whose implied positions
        it continues exactly (greedy parity past max_len; pinned by
        tests/test_speculative.py's chaos regression)."""
        cfg = self.cfg
        temperature = self.temperature
        rolling = self._rolling
        cap = None if rolling else jnp.int32(self._cap)

        def pick(k, row, q):
            return jax.random.categorical(jax.random.fold_in(k, q), row)

        def one(tcache, cur, pos, keys):
            logits, tcache = _decode_chunk(self.params, tcache,
                                           cur[:, None], pos, cfg)
            logits = logits[:, 0]
            if temperature > 0:
                nxt = jax.vmap(pick)(keys, logits / temperature, pos)
            else:
                nxt = logits.argmax(axis=-1)
            nxt = nxt.astype(jnp.int32)
            if rolling:
                adv = jnp.ones_like(pos)
                new_pos = pos + 1
            else:
                adv = (pos < cap).astype(jnp.int32)
                new_pos = jnp.minimum(pos + 1, cap)
            new_cur = jnp.where(adv > 0, nxt, cur)
            return tcache, new_cur, new_pos, nxt, adv

        return jax.jit(one, donate_argnums=0)

    def step(self):
        """One decode round for every lane; returns
        ``{lane: [tokens...]}`` — up to ``n_draft + 1`` tokens per
        lane per call (exactly 1 once the engine is degraded).  Runs
        under the engine lock, like :meth:`ContinuousBatcher.step`, so
        a concurrent locked ``submit``/``enqueue`` never rebinds the
        lane state mid-round-trip."""
        with self._admission_lock:
            return self._step_locked()

    def _step_locked(self):
        self.pump()
        if all(s is None or s.done for s in self._lane_state):
            return {}
        chaos.probe("serving.step")
        live = () if obs.active() is None else self.running()
        obs.gauge("serving.lanes_busy", len(live))
        if not self._degraded:
            try:
                chaos.probe("serving.draft")
                with obs.span("serving.step", speculative=True):
                    (tcache, dcache, prev, cur, pos, iters, win,
                     adv) = self._step(
                        self.tcache, self.dcache, self.prev, self.cur,
                        self.pos, self.keys, self.iters)
                    # Force async dispatch errors to surface INSIDE the
                    # try, before the engine state is rebound: a fault
                    # arriving here finds self.* still naming the donated
                    # (now consumed) inputs, and _note_draft_fault reports
                    # the unrecoverable case with a clear error instead of
                    # leaving poisoned state behind.
                    win, adv = np.asarray(win), np.asarray(adv)
            except Exception as e:  # noqa: BLE001 — degrade, not die
                self._note_draft_fault(e)
            else:
                (self.tcache, self.dcache, self.prev, self.cur,
                 self.pos, self.iters) = (tcache, dcache, prev, cur,
                                          pos, iters)
                if obs.active() is not None:
                    # Speculative accept rate, host-visible for free:
                    # each live lane advanced accepted + 1 positions.
                    accepted = int(sum(max(int(adv[l]) - 1, 0)
                                       for l in live))
                    obs.count("serving.spec.proposed",
                              self.n_draft * len(live))
                    obs.count("serving.spec.accepted", accepted)
                out = self._emit(
                    lambda lane: win[lane, :adv[lane]].tolist())
                self._reap()
                return out
        # Degraded: plain target decode — requests still complete.
        if self._fallback is None:
            self._fallback = self._make_fallback()
        with obs.span("serving.step", speculative=False):
            self.tcache, self.cur, self.pos, nxt, adv = self._fallback(
                self.tcache, self.cur, self.pos, self.keys)
            nxt, adv = np.asarray(nxt), np.asarray(adv)
        out = self._emit(
            lambda lane: [int(nxt[lane])] if adv[lane] else [])
        self._reap()
        return out
