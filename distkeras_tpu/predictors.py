"""Sharded batch inference (reference parity: distkeras/predictors.py).

The reference's ``ModelPredictor`` maps a Keras model over DataFrame
partitions inside Spark executors, appending a prediction column
(SURVEY.md §3.4).  Here the model's pure apply fn is jitted once with
the batch sharded over the mesh's ``data`` axis — every device runs a
slice of each batch — and the output lands as a new Dataset column.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.models.adapter import ModelAdapter
from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh


class Predictor:
    def predict(self, dataset: Dataset) -> Dataset:  # pragma: no cover
        raise NotImplementedError


class ModelPredictor(Predictor):
    """Append ``output_col`` = model(features) to a Dataset.

    Reference parity: distkeras/predictors.py::ModelPredictor
    (keras_model, features_col, output_col).  ``batch_size`` here is the
    *global* batch per jitted call; the tail batch is padded to keep the
    compiled shape static (one XLA program total) and trimmed after.
    """

    def __init__(self, keras_model, features_col: str = "features",
                 output_col: str = "prediction", batch_size: int = 1024,
                 mesh=None):
        self.adapter = ModelAdapter(keras_model, loss="mse")
        self.features_col = features_col
        self.output_col = output_col
        self.batch_size = batch_size
        self.mesh = mesh if mesh is not None else make_mesh(MeshSpec())
        # Jitted fn + device-resident weights are built once and reused
        # across predict() calls (one trace, one host->device transfer).
        n_data = int(self.mesh.shape["data"])
        bs = self.batch_size
        if bs % n_data:
            bs += n_data - (bs % n_data)  # keep batch divisible by mesh
        self._bs = bs
        self._data_sh = NamedSharding(self.mesh, P("data"))
        rep = NamedSharding(self.mesh, P())
        self._predict_fn = jax.jit(
            self.adapter.make_predict_fn(),
            in_shardings=(rep, rep, self._data_sh),
            out_shardings=self._data_sh,
        )
        self._tv = jax.device_put(
            [np.asarray(v.value) for v in self.adapter.model.trainable_variables], rep)
        self._ntv = jax.device_put(
            [np.asarray(v.value) for v in self.adapter.model.non_trainable_variables], rep)

    def _predict_array(self, x: np.ndarray) -> np.ndarray:
        bs = self._bs
        if len(x) == 0:
            # Empty poll (routine on streams): run one padded batch to
            # learn the output shape, return its 0-row slice.
            zero = np.zeros((bs,) + x.shape[1:], x.dtype)
            out = np.asarray(self._predict_fn(
                self._tv, self._ntv, jax.device_put(zero, self._data_sh)))
            return out[:0]
        outs = []
        for i in range(0, len(x), bs):
            xb = x[i:i + bs]
            pad = bs - len(xb)
            if pad:
                xb = np.concatenate([xb, np.zeros((pad,) + xb.shape[1:], xb.dtype)])
            yb = self._predict_fn(self._tv, self._ntv,
                                  jax.device_put(xb, self._data_sh))
            outs.append(np.asarray(yb)[:len(xb) - pad if pad else bs])
        return np.concatenate(outs)

    def predict(self, dataset: Dataset) -> Dataset:
        return dataset.with_column(
            self.output_col, self._predict_array(dataset[self.features_col]))

    def predict_stream(self, batches):
        """Yield predictions for an unbounded stream of feature arrays.

        The reference ships a Spark-Streaming/Kafka inference demo
        (reference: examples — streaming predictor over a DStream); the
        TPU-native equivalent is this generator: each incoming numpy
        array of features yields its prediction array, reusing the one
        jitted program and device-resident weights across the stream.
        """
        for xb in batches:
            yield self._predict_array(np.asarray(xb))
